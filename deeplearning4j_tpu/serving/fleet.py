"""ReplicaSupervisor — process supervision for a fleet of serving replicas.

PR 3's ResilientTrainer made *training* survive faults; this module is the
serving counterpart at fleet scope. One replica crash, one wedged batcher,
or one slow model must never take the endpoint down: the supervisor runs N
model-serving replicas, watches them the way a container runtime watches
pods, and keeps the fleet converged on "N healthy":

- **Probes with deadlines.** Every supervision tick, each replica is
  health-checked over its own HTTP surface: ``/healthz`` (liveness) then
  ``/readyz`` (warmed + not draining), each under ``probe_timeout_s``. A
  wedged replica — event loop alive but the process stuck — answers
  slowly or not at all; the deadline converts "slow" into "failed",
  which a bare TCP connect check never would.
- **Crash restarts with jittered exponential backoff.** A replica whose
  process died (SIGKILL, OOM, segfault) is relaunched after
  ``backoff * 2^attempt`` seconds, jittered to half its value so a
  correlated fleet-wide crash does not produce a synchronized restart
  stampede against the checkpoint store.
- **Drain + replace after K consecutive probe failures.** A replica that
  is alive but failed ``unhealthy_after`` probes in a row is presumed
  wedged: it is killed (a wedged process cannot be trusted to drain) and
  replaced by a fresh incarnation, bumping ``replica.generation`` so the
  router's circuit breakers start clean.
- **Restart budget.** More than ``restart_budget`` restarts inside
  ``restart_budget_window_s`` marks the replica ``dead`` (crash-looping —
  a bad model, a poisoned checkpoint, a broken host); the supervisor
  stops burning capacity on it and the gap shows on /metrics
  (`serving_fleet_replicas{state="dead"}`) for a human to page on.

Replicas come in two shapes sharing the `Replica` contract:
`SubprocessReplica` (a real ``python -m deeplearning4j_tpu.serving``
process — full isolation, SIGKILL-able, what `tools/serve_chaos.py`
drives) and `InProcessReplica` (a ModelServer in this process — cheap,
what most tests drive). The supervision logic never cares which.

Determinism: the supervision loop is a thin timer around `tick()`, and
`tick()` plus the injectable `time_fn` / `rng` / `probe_fn` seams make
every policy decision (backoff arithmetic, budget exhaustion, K-failure
replacement) unit-testable with a fake clock — no sleeps-and-hope.
"""
from __future__ import annotations

import json
import logging
import os
import queue as _queue
import subprocess
import sys
import threading
import time
import random as _random
import urllib.error
import urllib.request
from typing import Callable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import flight
from deeplearning4j_tpu.util.locks import DiagnosedLock

log = logging.getLogger("deeplearning4j_tpu")

#: replica lifecycle states (the serving_fleet_replicas{state} gauge keys).
#: "draining" is the autoscaler's scale-down limbo: out of the routing
#: set, finishing in-flight work, /readyz already answering not-ready.
REPLICA_STATES = ("starting", "ready", "unhealthy", "backoff", "dead",
                  "stopped", "draining")

#: rollout roles a replica can hold (serving/rollout.py sets these;
#: the router's canary split and /v1/fleet read them)
REPLICA_ROLES = ("stable", "canary")


class ReplicaSpec:
    """What one replica serves: the deploy arguments every incarnation of
    the replica is (re)built from."""

    def __init__(self, models: Sequence[Tuple[str, object]],
                 buckets: Sequence[int] = (1, 8, 32, 128),
                 max_delay_ms: float = 5.0, queue_limit: int = 256,
                 default_deadline_s: float = 30.0,
                 host: str = "127.0.0.1",
                 enable_faults: bool = False,
                 lms: Sequence[Tuple[str, object]] = (),
                 decode=None,
                 trace_out: Optional[str] = None,
                 postmortem_dir: Optional[str] = None,
                 flight: bool = True,
                 flight_records: int = 512,
                 slo_availability: Optional[float] = None,
                 slo_p99_ms: Optional[float] = None,
                 slo_sample_interval_s: float = 5.0,
                 slo_windows: Optional[str] = None,
                 kv_role: str = "mixed"):
        self.models = list(models)              # [(name, source), ...]
        self.buckets = tuple(int(b) for b in buckets)
        self.max_delay_ms = float(max_delay_ms)
        self.queue_limit = int(queue_limit)
        self.default_deadline_s = float(default_deadline_s)
        self.host = host
        self.enable_faults = bool(enable_faults)
        #: decode (LM) servables: [(name, source), ...] + one shared
        #: DecodeConfig (serving/decode.py); None decode = library default
        self.lms = list(lms)
        self.decode = decode
        #: base trace path: subprocess replicas save their own segment
        #: to <stem>.<replica-name><ext> on graceful drain, so
        #: tools/trace_report.py can merge the whole fleet
        self.trace_out = trace_out
        #: flight-recorder postmortem directory threaded to every replica
        self.postmortem_dir = postmortem_dir
        #: flight-recorder opt-out + ring size, threaded to every replica
        #: (an operator's --no-flight must disable the WHOLE fleet's
        #: recorder, not just the router's)
        self.flight = bool(flight)
        self.flight_records = int(flight_records)
        #: replica-side SLO engine knobs (monitor/slo.py), threaded as
        #: --slo-* flags so each subprocess replica runs its own
        #: objectives and the router's /v1/slo fan-out aggregates them
        self.slo_availability = (None if slo_availability is None
                                 else float(slo_availability))
        self.slo_p99_ms = None if slo_p99_ms is None else float(slo_p99_ms)
        self.slo_sample_interval_s = float(slo_sample_interval_s)
        self.slo_windows = slo_windows
        #: default KV-fabric disaggregation role for replicas built from
        #: this spec; a factory may override per replica (replica.kv_role)
        #: for mixed prefill/decode fleets
        if kv_role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f'kv_role must be "prefill", "decode" or "mixed", '
                f"got {kv_role!r}")
        self.kv_role = kv_role


class Replica:
    """One supervised serving replica. Subclasses provide the process
    mechanics (`launch` / `alive` / `kill` / `stop`); the supervisor and
    router only read the shared fields below."""

    def __init__(self, name: str, spec: Optional[ReplicaSpec] = None):
        self.name = name
        self.spec = spec
        self.url: Optional[str] = None
        self.state = "starting"
        self.generation = 0                  # bumps on every relaunch
        self.consecutive_probe_failures = 0
        # rollout state (serving/rollout.py): "canary" while this replica
        # serves a version under evaluation; rollout_generation bumps on
        # every rollout that touches the replica so operators can line up
        # /v1/fleet with the controller's decisions
        self.role = "stable"
        self.rollout_generation = 0
        # KV-fabric state: the disaggregation role this replica serves
        # under (spec default; factories override for split fleets) and
        # the prefix-ownership advertisement its /readyz heartbeat last
        # published ({model: {"block": N, "digests": [hex16...]}}) — the
        # router's affinity pick reads both
        self.kv_role = spec.kv_role if spec is not None else "mixed"
        self.kv_ownership: dict = {}
        # scale-down bookkeeping (autoscaler): None until this replica is
        # chosen as a drain victim, then a dict tracking the drain steps
        self.scaledown: Optional[dict] = None
        # router-maintained queue-depth signal (power-of-two-choices input)
        self._inflight = 0
        self._inflight_lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.fleet.Replica._inflight_lock")
        # supervisor restart bookkeeping
        self.restart_attempt = 0             # backoff exponent
        self.restart_at: Optional[float] = None
        self.restart_times: List[float] = []  # budget window

    # ------------------------------------------------------------ inflight
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def inflight_add(self, delta: int):
        with self._inflight_lock:
            self._inflight = max(0, self._inflight + delta)

    # ------------------------------------------------- subclass contract
    def launch(self):
        """(Re)start the replica; must set `self.url` or raise."""
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    def kill(self):
        """Hard-stop (crash analog / wedged process): no drain."""
        raise NotImplementedError

    def stop(self):
        """Graceful stop (drain in-flight work)."""
        self.kill()

    def begin_drain(self):
        """Start a graceful drain WITHOUT waiting for exit (the
        autoscaler's scale-down path): the replica should flip its own
        /readyz to not-ready and finish in-flight work; a later stop()
        reaps it. Default: nothing to signal — stop() does the drain."""

    def set_role(self, role: str, rollout_generation: int):
        """Mark this replica canary/stable (RolloutController). Subclasses
        propagate into the serving process so its own /readyz agrees with
        the fleet view."""
        self.role = role
        self.rollout_generation = int(rollout_generation)

    def describe(self) -> dict:
        doc = {"name": self.name, "url": self.url, "state": self.state,
               "generation": self.generation,
               "role": self.role,
               "rollout_generation": self.rollout_generation,
               "inflight": self.inflight(),
               "kv_role": self.kv_role,
               "probe_failures": self.consecutive_probe_failures}
        if self.kv_ownership:
            doc["kv_ownership"] = self.kv_ownership
        scaledown = getattr(self, "scaledown", None)
        if scaledown is not None:
            doc["scaledown"] = dict(scaledown)
        return doc


class InProcessReplica(Replica):
    """A ModelServer (own registry, own port) inside this process. Cheap
    replica for tests and single-host `--replica-mode inprocess` fleets;
    "crash" = hard listener+batcher stop without drain."""

    def __init__(self, name: str, spec: ReplicaSpec):
        super().__init__(name, spec)
        self._server = None
        self._registry = None

    def launch(self):
        from deeplearning4j_tpu.serving.registry import ModelRegistry
        from deeplearning4j_tpu.serving.server import ModelServer
        from deeplearning4j_tpu.util.faults import ServingFaults
        registry = ModelRegistry()
        for model_name, source in self.spec.models:
            registry.deploy(model_name, source, buckets=self.spec.buckets,
                            max_delay_ms=self.spec.max_delay_ms,
                            queue_limit=self.spec.queue_limit)
        for model_name, source in self.spec.lms:
            registry.deploy_lm(model_name, source, decode=self.spec.decode)
        self._registry = registry
        self._server = ModelServer(
            registry, host=self.spec.host, port=0,
            default_deadline_s=self.spec.default_deadline_s,
            enable_faults=self.spec.enable_faults,
            # own instance: wedging THIS replica must not wedge every
            # in-process sibling through the module singleton
            faults=ServingFaults(),
            kv_role=self.kv_role)
        self.url = self._server.url

    def alive(self) -> bool:
        return self._server is not None and self._server._thread.is_alive()

    def kill(self):
        if self._server is not None:
            self._server.stop()
        if self._registry is not None:
            self._registry.shutdown(drain=False)
        self._server = self._registry = None

    def stop(self):
        if self._server is not None:
            self._server.drain(timeout=10.0)
        self._server = self._registry = None

    def begin_drain(self):
        if self._server is not None:
            self._server.draining = True     # /readyz -> 503 immediately

    def set_role(self, role: str, rollout_generation: int):
        super().set_role(role, rollout_generation)
        if self._server is not None:
            self._server.role = role
            self._server.rollout_generation = int(rollout_generation)


class SubprocessReplica(Replica):
    """A real ``python -m deeplearning4j_tpu.serving`` child process —
    full crash isolation (SIGKILL-able, OOM-able), its own XLA runtime,
    its own /metrics. The CLI fleet mode and tools/serve_chaos.py run
    these. The child binds port 0 and announces its URL as the first JSON
    line on stdout; launch() blocks until that line (or the deadline)."""

    def __init__(self, name: str, spec: ReplicaSpec,
                 env: Optional[dict] = None,
                 launch_timeout_s: float = 180.0):
        super().__init__(name, spec)
        self.proc: Optional[subprocess.Popen] = None
        self.env = env
        self.launch_timeout_s = float(launch_timeout_s)

    def _argv(self) -> List[str]:
        argv = [sys.executable, "-m", "deeplearning4j_tpu.serving",
                "--host", self.spec.host, "--port", "0",
                "--buckets", ",".join(str(b) for b in self.spec.buckets),
                "--max-delay-ms", str(self.spec.max_delay_ms),
                "--queue-limit", str(self.spec.queue_limit),
                "--deadline-s", str(self.spec.default_deadline_s)]
        for model_name, source in self.spec.models:
            if not isinstance(source, str):
                raise TypeError(
                    f"subprocess replica {self.name}: model source must be "
                    f"a path/zoo name string, got {type(source).__name__}")
            argv += ["--model", f"{model_name}={source}"]
        for model_name, source in self.spec.lms:
            if not isinstance(source, str):
                raise TypeError(
                    f"subprocess replica {self.name}: LM source must be "
                    f"a path/zoo name string, got {type(source).__name__}")
            argv += ["--lm", f"{model_name}={source}"]
        if self.spec.lms and self.spec.decode is not None:
            d = self.spec.decode
            argv += ["--decode-slots", str(d.slots),
                     "--decode-page-size", str(d.page_size),
                     "--decode-queue-limit", str(d.queue_limit)]
            if d.max_context is not None:
                argv += ["--decode-max-context", str(d.max_context)]
            if d.pool_pages is not None:
                argv += ["--decode-pool-pages", str(d.pool_pages)]
            if d.prefill_buckets:
                argv += ["--prefill-buckets",
                         ",".join(str(b) for b in d.prefill_buckets)]
            if d.prefill_chunk_tokens is not None:
                argv += ["--prefill-chunk-tokens",
                         str(d.prefill_chunk_tokens)]
            if not d.prefix_cache:
                argv.append("--no-prefix-cache")
            if d.spill_pages:
                argv += ["--kv-spill-pages", str(d.spill_pages)]
            if d.spec_draft is not None:
                argv += ["--spec-draft", str(d.spec_draft),
                         "--spec-k", str(d.spec_k),
                         "--spec-accept-floor", str(d.spec_accept_floor),
                         "--spec-window", str(d.spec_window)]
                if d.spec_draft_pool_pages is not None:
                    argv += ["--spec-draft-pool-pages",
                             str(d.spec_draft_pool_pages)]
        if self.spec.lms and self.kv_role != "mixed":
            argv += ["--kv-role", self.kv_role]
        if self.spec.enable_faults:
            argv.append("--enable-fault-injection")
        if self.spec.trace_out:
            stem, ext = os.path.splitext(self.spec.trace_out)
            argv += ["--trace-out", f"{stem}.{self.name}{ext or '.json'}"]
        if self.spec.postmortem_dir:
            argv += ["--postmortem-dir", self.spec.postmortem_dir]
        if not self.spec.flight:
            argv.append("--no-flight")
        elif self.spec.flight_records != 512:
            argv += ["--flight-records", str(self.spec.flight_records)]
        if self.spec.slo_availability is not None:
            argv += ["--slo-availability", str(self.spec.slo_availability)]
        if self.spec.slo_p99_ms is not None:
            argv += ["--slo-p99-ms", str(self.spec.slo_p99_ms)]
        if (self.spec.slo_availability is not None
                or self.spec.slo_p99_ms is not None):
            argv += ["--slo-sample-interval-s",
                     str(self.spec.slo_sample_interval_s)]
            if self.spec.slo_windows:
                argv += ["--slo-windows", self.spec.slo_windows]
        return argv

    def launch(self):
        self.proc = subprocess.Popen(
            self._argv(), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, env=self.env, text=True)
        # a silent hung child must not hang launch(): readline() has no
        # deadline of its own, so a reader thread feeds a queue and the
        # timeout lives on the queue get. The thread exits on the EOF
        # that kill() forces.
        proc, lineq = self.proc, _queue.Queue()

        def _read_stdout():
            try:
                for out_line in proc.stdout:
                    lineq.put(out_line)
            except Exception:                 # noqa: BLE001 — fail loud:
                # a dead reader must not leave launch() waiting out its
                # whole deadline on a queue nobody will ever feed
                log.exception("fleet: %s stdout reader failed", self.name)
            finally:
                lineq.put(None)               # EOF/failure marker

        threading.Thread(target=_read_stdout, daemon=True,
                         name=f"{self.name}-stdout").start()
        deadline = time.monotonic() + self.launch_timeout_s
        while True:
            try:
                line = lineq.get(
                    timeout=max(0.0, deadline - time.monotonic()))
            except _queue.Empty:
                self.kill()
                raise TimeoutError(
                    f"replica {self.name}: no startup announcement within "
                    f"{self.launch_timeout_s:.0f}s")
            if line is None:                  # EOF — child died in startup
                rc = self.proc.poll()
                raise RuntimeError(
                    f"replica {self.name}: exited rc={rc} before "
                    "announcing its URL")
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if isinstance(doc, dict) and doc.get("serving"):
                self.url = doc["serving"]
                return

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)

    def stop(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()            # SIGTERM -> CLI drains
            try:
                self.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                self.kill()

    def begin_drain(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()            # SIGTERM: CLI flips /readyz
            # 503 and drains in-flight work; the child exits on its own

    def set_role(self, role: str, rollout_generation: int):
        super().set_role(role, rollout_generation)
        if self.url is None:
            return
        # best-effort push into the child so ITS /readyz agrees with the
        # fleet view; the supervisor-side fields above stay authoritative
        # for routing even if the child is briefly unreachable
        body = json.dumps({"role": role,
                           "rollout_generation": int(rollout_generation)})
        req = urllib.request.Request(
            f"{self.url}/v1/rollout/role", data=body.encode("utf-8"),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=5.0):
                pass
        except (urllib.error.URLError, OSError) as e:
            log.warning("fleet: %s role push failed: %s", self.name, e)


class AutoscaleConfig:
    """Load-signal autoscaling policy: track traffic, not a --replicas
    flag. The signal is the router-maintained in-flight count (the same
    queue-depth input power-of-two-choices balances on) against healthy
    capacity: ``utilization = sum(inflight) / (healthy * capacity)``.

    - utilization >= ``high_watermark`` for ``up_after_ticks`` consecutive
      supervision ticks -> add one replica (launched through the same
      spawn/generation/restart-budget machinery as a relaunch);
    - utilization <= ``low_watermark`` for ``down_after_ticks`` ticks ->
      retire one replica by DRAINING it: out of the routing set first,
      its own /readyz confirmed not-ready, in-flight work finished, then
      a graceful stop — never a kill (a forced kill after
      ``drain_timeout_s`` is counted loudly on /metrics);
    - one scaling action per ``cooldown_s``, canaries are never victims,
      and the count stays inside [min_replicas, max_replicas].
    """

    def __init__(self, min_replicas: int, max_replicas: int,
                 capacity_per_replica: int,
                 high_watermark: float = 0.8,
                 low_watermark: float = 0.25,
                 up_after_ticks: int = 2,
                 down_after_ticks: int = 5,
                 cooldown_s: float = 10.0,
                 drain_timeout_s: float = 30.0):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.capacity_per_replica = int(capacity_per_replica)
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.up_after_ticks = int(up_after_ticks)
        self.down_after_ticks = int(down_after_ticks)
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_s = float(drain_timeout_s)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                "autoscale needs 1 <= min_replicas <= max_replicas, got "
                f"[{self.min_replicas}, {self.max_replicas}]")
        if self.capacity_per_replica < 1:
            raise ValueError("autoscale capacity_per_replica must be >= 1")
        if not 0.0 < self.low_watermark < self.high_watermark <= 1.0:
            raise ValueError(
                "autoscale needs 0 < low_watermark < high_watermark <= 1, "
                f"got ({self.low_watermark}, {self.high_watermark})")


def _threaded_spawn(fn: Callable[[], None], name: str):
    """Default relaunch spawner: a daemon thread, returned for joining.
    Tests inject a synchronous spawner to keep tick() deterministic."""
    t = threading.Thread(target=fn, daemon=True, name=name)
    t.start()
    return t


def http_probe(replica: Replica, timeout: float) -> bool:
    """Default probe: /healthz then /readyz, each 200 within `timeout`.
    The /readyz body doubles as the KV-fabric heartbeat: its kv_role and
    kv_ownership fields are stashed on the replica handle so the router's
    prefix-affinity pick always works from the latest advertisement."""
    if not replica.url:
        return False
    body = b""
    for path in ("/healthz", "/readyz"):
        try:
            r = urllib.request.urlopen(replica.url + path, timeout=timeout)
            if r.status != 200:
                return False
            body = r.read()
        except Exception:                     # noqa: BLE001 — any failure
            return False                      # (timeout, 5xx, conn refused)
    try:
        doc = json.loads(body)
    except ValueError:
        return True                           # pre-fabric replica: fine
    if isinstance(doc, dict):
        if doc.get("kv_role") in ("prefill", "decode", "mixed"):
            replica.kv_role = doc["kv_role"]
        own = doc.get("kv_ownership")
        if isinstance(own, dict):
            replica.kv_ownership = own
    return True


class ReplicaSupervisor:
    """Keep N replicas healthy: probe, restart, replace, give up loudly.

    Usage (production shape):

        sup = ReplicaSupervisor(
            lambda i: SubprocessReplica(f"replica-{i}", spec), n_replicas=3)
        sup.start()                   # launch all, wait until ready
        ...
        sup.healthy()                 # the router's routing set
        sup.stop()

    Tests drive `tick()` directly with injected `time_fn`/`probe_fn`.
    """

    def __init__(self, factory: Callable[[int], Replica], n_replicas: int,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 2.0,
                 unhealthy_after: int = 3,
                 restart_backoff_s: float = 0.5,
                 restart_backoff_max_s: float = 30.0,
                 restart_budget: int = 5,
                 restart_budget_window_s: float = 600.0,
                 start_deadline_s: float = 300.0,
                 time_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 rng: Optional[_random.Random] = None,
                 probe_fn: Callable[[Replica, float], bool] = http_probe,
                 spawn_fn: Callable = _threaded_spawn,
                 autoscale: Optional[AutoscaleConfig] = None):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if autoscale is not None and not (
                autoscale.min_replicas <= n_replicas
                <= autoscale.max_replicas):
            raise ValueError(
                f"n_replicas={n_replicas} outside the autoscale range "
                f"[{autoscale.min_replicas}, {autoscale.max_replicas}]")
        self.replicas = [factory(i) for i in range(int(n_replicas))]
        names = [r.name for r in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique: {names}")
        self.probe_interval = float(probe_interval_s)
        self.probe_timeout = float(probe_timeout_s)
        self.unhealthy_after = int(unhealthy_after)
        self.backoff = float(restart_backoff_s)
        self.backoff_max = float(restart_backoff_max_s)
        self.restart_budget = int(restart_budget)
        self.budget_window = float(restart_budget_window_s)
        self.start_deadline = float(start_deadline_s)
        self._time = time_fn
        self._sleep = sleep_fn
        self._rng = rng if rng is not None else _random.Random()
        self._probe = probe_fn
        self._spawn = spawn_fn
        self.autoscale = autoscale
        self._factory = factory
        self._next_index = int(n_replicas)   # names for scaled-up replicas
        self._ticks_above = 0                # consecutive high-utilization
        self._ticks_below = 0                # consecutive low-utilization
        self._scale_ok_at = 0.0              # cooldown gate
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.fleet.ReplicaSupervisor._lock"
        )                                    # serializes tick vs stop

    # ------------------------------------------------------------- metrics
    def _note_restart(self, replica: Replica, reason: str):
        monitor.counter(
            "serving_fleet_restarts_total",
            "Replica restarts by the supervisor (reason: crash = process "
            "died, probe = K consecutive probe failures, launch = "
            "relaunch itself failed)",
            labels=("replica", "reason")).inc(replica=replica.name,
                                              reason=reason)

    def _export_states(self):
        counts = {s: 0 for s in REPLICA_STATES}
        for r in self.replicas:
            counts[r.state] = counts.get(r.state, 0) + 1
        g = monitor.gauge("serving_fleet_replicas",
                          "Replica count per lifecycle state",
                          labels=("state",))
        for s, n in counts.items():
            g.set(n, state=s)
        monitor.gauge("serving_fleet_size",
                      "Configured replica count").set(len(self.replicas))

    # ------------------------------------------------------------ lifecycle
    def start(self, wait_ready: bool = True):
        """Launch every replica (in parallel — subprocess replicas pay a
        runtime-import each), then optionally block until the whole fleet
        probes ready, then start the supervision loop."""
        errors: List[str] = []

        def _launch(r: Replica):
            try:
                r.launch()
            except Exception as e:            # noqa: BLE001
                errors.append(f"{r.name}: {type(e).__name__}: {e}")
                r.state = "unhealthy"

        threads = [threading.Thread(target=_launch, args=(r,), daemon=True,
                                    name=f"launch-{r.name}")
                   for r in self.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            self.stop_replicas()
            raise RuntimeError("fleet launch failed: " + "; ".join(errors))
        if wait_ready:
            deadline = self._time() + self.start_deadline
            pending = list(self.replicas)
            while pending:
                pending = [r for r in pending
                           if not self._probe_once(r, mark=True)]
                if not pending:
                    break
                if self._time() > deadline:
                    self.stop_replicas()
                    raise TimeoutError(
                        "fleet not ready within "
                        f"{self.start_deadline:.0f}s: "
                        f"{[r.name for r in pending]} still unready")
                self._sleep(0.2)
        self._export_states()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ReplicaSupervisor")
        self._thread.start()
        log.info("fleet: supervising %d replicas (%s)", len(self.replicas),
                 ", ".join(f"{r.name}@{r.url}" for r in self.replicas))

    def _run(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:                 # noqa: BLE001 — keep watching
                log.exception("fleet: supervision tick failed")
            self._sleep(self.probe_interval)

    def healthy(self) -> List[Replica]:
        return [r for r in self.replicas if r.state == "ready"]

    def describe(self) -> dict:
        doc = {"replicas": [r.describe() for r in self.replicas]}
        if self.autoscale is not None:
            cfg = self.autoscale
            doc["autoscale"] = {
                "min_replicas": cfg.min_replicas,
                "max_replicas": cfg.max_replicas,
                "capacity_per_replica": cfg.capacity_per_replica,
                "high_watermark": cfg.high_watermark,
                "low_watermark": cfg.low_watermark,
            }
        return doc

    def stop_replicas(self):
        for r in self.replicas:
            try:
                r.stop()
            except Exception:                 # noqa: BLE001
                log.exception("fleet: stopping %s failed", r.name)
            r.state = "stopped"
        self._export_states()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, 2 * self.probe_interval))
        # give in-flight relaunches a moment to notice the stop flag and
        # clean up their own fresh processes; a hung one stays daemon
        self._join_relaunches(timeout=5.0)
        with self._lock:
            self.stop_replicas()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------- the tick
    def _probe_once(self, replica: Replica, mark: bool = False) -> bool:
        t0 = time.perf_counter()
        with monitor.span("serving/probe", replica=replica.name):
            ok = self._probe(replica, self.probe_timeout)
        monitor.histogram("serving_fleet_probe_seconds",
                          "Health-probe round-trip time",
                          labels=("replica",)).observe(
            time.perf_counter() - t0, replica=replica.name)
        if ok and mark:
            replica.state = "ready"
            replica.consecutive_probe_failures = 0
        return ok

    def tick(self):
        """One supervision pass. Deterministic given time_fn/probe_fn:
        probes live replicas, schedules/executes restarts, enforces the
        budget. Called by the loop every probe_interval; tests call it
        directly. Relaunches run via `spawn_fn` (a daemon thread by
        default) so one slow or hung launch never stalls supervision of
        the rest of the fleet — or supervisor.stop()."""
        due: List[Replica] = []
        wedged: List[Tuple[str, int, int]] = []   # postmortems after lock
        with self._lock:
            if self._stop.is_set():
                return
            now = self._time()
            live: List[Replica] = []
            for r in self.replicas:
                if r.state in ("dead", "stopped"):
                    continue
                launching = getattr(r, "_launch_thread", None)
                if launching is not None and launching.is_alive():
                    continue              # relaunch in flight: hands off
                if r.state == "backoff":
                    if now >= (r.restart_at or 0):
                        # transition under the lock BEFORE spawning so
                        # the next tick cannot double-launch
                        r.generation += 1
                        r.consecutive_probe_failures = 0
                        r.restart_at = None
                        r.state = "starting"
                        due.append(r)
                    continue
                if not r.alive():
                    log.warning("fleet: %s process died — scheduling "
                                "restart", r.name)
                    self._note_restart(r, "crash")
                    self._schedule_restart(r, now)
                    continue
                live.append(r)
            # probe live replicas CONCURRENTLY: N wedged replicas cost
            # one probe window per tick, not N of them (each probe is
            # already deadline-bounded by probe_timeout)
            probe_ok = {}
            if len(live) == 1:
                probe_ok[live[0].name] = self._probe_once(live[0])
            elif live:
                probers = [threading.Thread(
                    target=lambda r=r: probe_ok.__setitem__(
                        r.name, self._probe_once(r)),
                    daemon=True, name=f"probe-{r.name}") for r in live]
                for t in probers:
                    t.start()
                for t in probers:
                    # graftlint: disable=blocking-under-lock -- each probe thread is deadline-bounded by probe_timeout (never unbounded); tick() deliberately holds its lock for ONE bounded probe window (PR-8 design)
                    t.join()
            for r in live:
                if probe_ok[r.name]:
                    if r.state != "ready":
                        log.info("fleet: %s is ready (gen %d)", r.name,
                                 r.generation)
                    r.state = "ready"
                    r.consecutive_probe_failures = 0
                    r.restart_attempt = 0    # stable again: backoff resets
                    continue
                r.consecutive_probe_failures += 1
                monitor.counter("serving_fleet_probe_failures_total",
                                "Failed health probes",
                                labels=("replica",)).inc(replica=r.name)
                # a replica still "starting" (warming its bucket ladder)
                # gets 5x the probe patience before it is presumed wedged
                patience = self.unhealthy_after * (
                    5 if r.state == "starting" else 1)
                if r.consecutive_probe_failures >= patience:
                    # alive but failing probes = wedged. A wedged process
                    # cannot be trusted to drain — kill and replace.
                    log.warning(
                        "fleet: %s failed %d consecutive probes — "
                        "presumed wedged, replacing", r.name,
                        r.consecutive_probe_failures)
                    r.state = "unhealthy"
                    self._note_restart(r, "probe")
                    wedged.append((r.name, r.generation,
                                   r.consecutive_probe_failures))
                    try:
                        r.kill()
                    except Exception:         # noqa: BLE001
                        log.exception("fleet: killing wedged %s failed",
                                      r.name)
                    self._schedule_restart(r, now)
            grow, shrink = self._autoscale_tick(now)
            self._export_states()
        for name, gen, probe_failures in wedged:
            # OUTSIDE the tick lock (postmortems write a file): a wedge
            # detection is an SLO event — dump the flight ring naming
            # the replica incarnation that wedged
            flight.trip("replica_wedged", replica=name, generation=gen,
                        probe_failures=probe_failures)
        for r in due:
            r._launch_thread = self._spawn(
                lambda r=r: self._relaunch(r), f"relaunch-{r.name}")
        for r in grow:
            r._launch_thread = self._spawn(
                lambda r=r: self._relaunch(r), f"scale-up-{r.name}")
        for r in shrink:
            r._drain_thread = self._spawn(
                lambda r=r: self._drain_retired(r), f"drain-{r.name}")

    # ---------------------------------------------------------- autoscaling
    def _autoscale_tick(self, now: float):
        """One autoscale evaluation (called under the tick lock). Returns
        (replicas to launch, replicas to drain) for the caller to spawn
        OUTSIDE the lock — same discipline as relaunches."""
        cfg = self.autoscale
        if cfg is None:
            return [], []
        # retired replicas whose drain finished leave the roster entirely
        # (a scaled-down replica is gone, not a gap to alert on)
        self.replicas = [
            r for r in self.replicas
            if not (r.state == "stopped"
                    and getattr(r, "scaledown", None) is not None)]
        ready = [r for r in self.replicas if r.state == "ready"]
        # anything not permanently gone still counts against max_replicas:
        # a starting or backoff replica is capacity in flight
        active = [r for r in self.replicas
                  if r.state not in ("dead", "stopped", "draining")]
        capacity = len(ready) * cfg.capacity_per_replica
        demand = sum(r.inflight() for r in ready)
        # no ready capacity but demand pressure cannot be measured — treat
        # as saturated only if there's nothing coming up already
        util = (demand / capacity) if capacity else (
            1.0 if not active else 0.0)
        monitor.gauge("serving_autoscale_utilization",
                      "Router-tracked in-flight demand over healthy "
                      "capacity (the autoscaler's input signal)"
                      ).set(round(util, 4))
        self._ticks_above = self._ticks_above + 1 \
            if util >= cfg.high_watermark else 0
        self._ticks_below = self._ticks_below + 1 \
            if util <= cfg.low_watermark else 0
        if now < self._scale_ok_at:
            return [], []
        events = monitor.counter(
            "serving_autoscale_events_total",
            "Autoscaler scaling actions (direction: up = replica added, "
            "down = replica drained out)", labels=("direction",))
        if self._ticks_above >= cfg.up_after_ticks \
                and len(active) < cfg.max_replicas:
            name_index = self._next_index
            self._next_index += 1
            replica = self._factory(name_index)
            replica.state = "starting"
            self.replicas.append(replica)
            self._ticks_above = 0
            self._scale_ok_at = now + cfg.cooldown_s
            events.inc(direction="up")
            log.info("fleet: autoscale up -> launching %s "
                     "(utilization %.2f over %d ready)", replica.name,
                     util, len(ready))
            return [replica], []
        if self._ticks_below >= cfg.down_after_ticks \
                and len(active) > cfg.min_replicas:
            # victim: the youngest READY stable replica — canaries are
            # under rollout evaluation and must never be drained away
            victims = [r for r in ready if r.role != "canary"]
            if not victims:
                return [], []
            victim = victims[-1]
            victim.state = "draining"
            victim.scaledown = {"readyz_confirmed": False,
                                "forced_kill": False}
            self._ticks_below = 0
            self._scale_ok_at = now + cfg.cooldown_s
            events.inc(direction="down")
            log.info("fleet: autoscale down -> draining %s "
                     "(utilization %.2f over %d ready)", victim.name,
                     util, len(ready))
            return [], [victim]
        return [], []

    def _drain_retired(self, replica: Replica):
        """Scale-down teardown, OFF the tick lock: the replica already
        left the routing set (state 'draining'); signal the drain, wait
        for its own /readyz to confirm not-ready, wait out in-flight
        work, then stop gracefully. Killing is the loud last resort after
        drain_timeout_s, never the plan."""
        cfg = self.autoscale
        try:
            replica.begin_drain()
        except Exception:                     # noqa: BLE001
            log.exception("fleet: begin_drain on %s failed", replica.name)
        deadline = self._time() + cfg.drain_timeout_s
        # the replica itself must acknowledge the drain: its probe
        # (healthz+readyz) failing is the /readyz-flipped-503 signal
        while self._time() < deadline and not self._stop.is_set():
            if not self._probe(replica, self.probe_timeout):
                replica.scaledown["readyz_confirmed"] = True
                break
            self._sleep(min(0.2, self.probe_interval))
        while replica.inflight() > 0 and self._time() < deadline \
                and not self._stop.is_set():
            self._sleep(min(0.2, self.probe_interval))
        try:
            replica.stop()                   # graceful reap
        except Exception:                     # noqa: BLE001
            log.exception("fleet: draining stop of %s failed", replica.name)
        if replica.alive():
            replica.scaledown["forced_kill"] = True
            monitor.counter(
                "serving_autoscale_forced_kills_total",
                "Scale-down drains that exhausted drain_timeout_s and "
                "fell back to a kill (should be zero)",
                labels=("replica",)).inc(replica=replica.name)
            log.warning("fleet: %s did not drain within %.0fs — killing",
                        replica.name, cfg.drain_timeout_s)
            try:
                replica.kill()
            except Exception:                 # noqa: BLE001
                log.exception("fleet: kill of undrained %s failed",
                              replica.name)
        with self._lock:
            replica.state = "stopped"
            self._export_states()

    def _schedule_restart(self, replica: Replica, now: float):
        replica.restart_times = [t for t in replica.restart_times
                                 if now - t <= self.budget_window]
        if len(replica.restart_times) >= self.restart_budget:
            log.error(
                "fleet: %s exceeded its restart budget (%d restarts in "
                "%.0fs) — marking dead; a human should look at it",
                replica.name, len(replica.restart_times),
                self.budget_window)
            monitor.counter("serving_fleet_gave_up_total",
                            "Replicas abandoned after exhausting the "
                            "restart budget (crash loop)",
                            labels=("replica",)).inc(replica=replica.name)
            replica.state = "dead"
            try:
                replica.kill()
            # graftlint: disable=bare-except-swallow -- best-effort kill of an already-dead-to-us process; state=dead + serving_fleet_gave_up_total above are the observable record
            except Exception:                 # noqa: BLE001
                pass
            return
        replica.restart_times.append(now)
        # jittered exponential backoff: full value down to half of it, so
        # a correlated crash doesn't restart the whole fleet in lockstep
        delay = min(self.backoff_max,
                    self.backoff * (2 ** replica.restart_attempt))
        delay *= 0.5 + 0.5 * self._rng.random()
        replica.restart_attempt += 1
        replica.restart_at = now + delay
        replica.state = "backoff"
        log.warning("fleet: restarting %s in %.2fs (attempt %d)",
                    replica.name, delay, replica.restart_attempt)

    def _relaunch(self, replica: Replica):
        """Launch a fresh incarnation. Runs OUTSIDE the tick lock (on a
        spawn_fn thread in production): only the post-launch bookkeeping
        re-acquires it. tick() already moved the replica to 'starting'."""
        with monitor.span("serving/restart", replica=replica.name,
                          generation=replica.generation):
            try:
                replica.launch()
            except Exception as e:            # noqa: BLE001
                log.error("fleet: relaunching %s failed: %s: %s",
                          replica.name, type(e).__name__, e)
                with self._lock:
                    if self._stop.is_set():
                        return
                    self._note_restart(replica, "launch")
                    self._schedule_restart(replica, self._time())
                return
        with self._lock:
            if self._stop.is_set():
                # stop() raced the relaunch: don't leak a fresh process
                try:
                    replica.stop()
                # graftlint: disable=bare-except-swallow -- best-effort teardown of a stop-raced fresh process; state=stopped below is the record and stop() must not raise
                except Exception:             # noqa: BLE001
                    pass
                replica.state = "stopped"
                return
        log.info("fleet: relaunched %s (gen %d) at %s", replica.name,
                 replica.generation, replica.url)

    def _join_relaunches(self, timeout: float = 30.0):
        for r in self.replicas:
            for attr in ("_launch_thread", "_drain_thread"):
                t = getattr(r, attr, None)
                if t is not None:
                    t.join(timeout)
