"""Paged KV cache — fixed-shape attention memory for continuous batching.

Autoregressive decode is memory-shaped like virtual memory, not like a
tensor: each in-flight sequence grows one (key, value) row per layer per
token, sequences join and leave at arbitrary times, and naive per-sequence
contiguous buffers either fragment HBM or force worst-case preallocation
per request. The PagedAttention design (Kwon et al., SOSP'23) maps the OS
page table onto the KV cache, and this module is that design sized for the
AOT-warm serving contract of `serving/batcher.py`:

- **One physical pool, fixed at load time.** Per layer, keys and values
  live in ``(L, P, page_size, H, D)`` arrays allocated once when the
  servable loads. Every compiled program sees these exact shapes forever —
  no growth, no reallocation, no shape-driven recompiles.
- **Per-slot page tables.** Each decode *slot* (a fixed position in the
  running batch) owns a row of ``max_context // page_size`` physical page
  ids. Logical position ``t`` of a sequence lives at physical page
  ``table[t // page_size]``, offset ``t % page_size`` — pages are
  allocated on demand as the sequence crosses page boundaries.
- **The dump page.** Physical page 0 is never allocated. Fixed-shape
  scatter writes from *inactive* slots and from prompt-padding positions
  are steered to page 0, so the compiled step never needs a dynamic shape
  or a conditional write — garbage goes to a page nobody reads.

Page sharing — carrying the virtual-memory analogy to completion
(RadixAttention/SGLang over PagedAttention/vLLM):

- **Refcounts + a radix index.** Every full, page-aligned block of prompt
  tokens is keyed by its token bytes in a trie rooted at the empty prefix;
  a trie node maps that block (in the context of its ancestors) to the one
  canonical physical page holding its K/V. ``admit_prompt()`` walks the
  trie over the new prompt's blocks and maps every matched page
  *read-shared* into the new slot's page table (refcount + 1 per mapping);
  only the uncached suffix still needs prefill compute. Repeated
  system-prompt prefill collapses into page-table pointer writes.
- **Copy-on-write.** Shared pages are never written: prompt blocks are
  immutable once prefilled (decode appends land at ``seq_len >=
  prompt_len``, past every full block), so sharing is read-only by
  construction — except when a prompt is page-aligned and *fully* cached.
  At least one token must still be recomputed to produce first-token
  logits, and that write would land inside the last shared page, so admit
  hands back a (src, dst) pair: the engine copies the page on-device and
  the slot diverges on its private copy. The dump page is never indexed,
  never shared, never a COW source.
- **Release retains, pressure evicts.** ``release()`` decrements
  refcounts; indexed pages that reach zero move to an LRU *retained set*
  instead of the free list — a hot prefix's K/V survives across requests.
  Allocation takes free pages first and evicts retained pages (LRU,
  leaf-preferring so a chain's tail goes before its root; evicting a node
  unindexes its whole subtree) only under pool pressure. Un-indexed pages
  (partial prompt tails, generated tokens, token-less ``admit()``) free
  immediately, exactly as before.

The host side (`KVCacheState`) is plain numpy + free lists: allocation
decisions happen between compiled steps, and the page table crosses to the
device as a small int32 operand each step. The device side is pure
gather/scatter helpers used inside the jitted prefill/decode programs.
"""
from __future__ import annotations

import dataclasses
import hashlib
import logging
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.serving import kvfabric
from deeplearning4j_tpu.util.locks import DiagnosedLock

log = logging.getLogger("deeplearning4j_tpu")

#: physical page 0 — the write sink for inactive slots / padded positions.
DUMP_PAGE = 0


class PagePoolExhaustedError(RuntimeError):
    """No free physical page for a sequence that must grow (the caller
    stalls the slot or sheds the join; this never crashes a step)."""


@dataclasses.dataclass(frozen=True)
class AdmitInfo:
    """Result of a token-aware admission (`admit_prompt`).

    cached_len prompt positions are already present in pages mapped
    read-shared into the slot; prefill only needs [cached_len, len).
    When the whole (page-aligned) prompt was cached, cow_src/cow_dst name
    the page the engine must copy before the forced last-token recompute
    writes into it — the copy-on-write divergence point."""
    slot: int
    cached_len: int
    cow_src: Optional[int] = None
    cow_dst: Optional[int] = None


class _RadixNode:
    """One full token block in the context of its ancestors -> the
    canonical physical page holding its K/V."""

    __slots__ = ("key", "parent", "children", "page", "digest")

    def __init__(self, key: Optional[bytes], parent: "Optional[_RadixNode]",
                 page: int = DUMP_PAGE, digest: bytes = b""):
        self.key = key
        self.parent = parent
        self.children: Dict[bytes, "_RadixNode"] = {}
        self.page = page
        #: chained prefix-path digest (kvfabric.chain_digests semantics):
        #: the page's identity in the spill tier and on the wire
        self.digest = digest


class KVCacheState:
    """Host-side bookkeeping for one engine's paged KV cache.

    Owns the slot free list, the physical-page free list, per-page
    refcounts, the radix prefix index + LRU retained set, and the numpy
    page table / sequence lengths mirrored into every compiled step.
    Thread-safe (admissions come from the scheduler thread, releases can
    race drains), but the expected driver is a single scheduler loop.
    """

    def __init__(self, slots: int, page_size: int, max_context: int,
                 pool_pages: Optional[int] = None, name: str = "lm",
                 prefix_cache: bool = True):
        if page_size < 1 or slots < 1:
            raise ValueError(f"slots/page_size must be >= 1 "
                             f"(got {slots}/{page_size})")
        if max_context % page_size:
            raise ValueError(
                f"max_context {max_context} must be a multiple of "
                f"page_size {page_size}")
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.max_context = int(max_context)
        self.pages_per_slot = self.max_context // self.page_size
        # +1: the dump page. The default pool is NOT oversubscribed (every
        # slot can reach max_context); size it smaller to oversubscribe
        # memory against typical sequence lengths — joins then wait on the
        # free list when the pool runs dry.
        self.pool_pages = int(pool_pages) if pool_pages is not None \
            else 1 + self.slots * self.pages_per_slot
        if self.pool_pages < 1 + self.pages_per_slot:
            raise ValueError(
                f"pool_pages {self.pool_pages} cannot hold even one "
                f"max-context sequence ({1 + self.pages_per_slot} needed)")
        self.name = name
        self.prefix_cache = bool(prefix_cache)
        self._lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.kvcache.KVCacheState._lock")
        #: logical->physical page map per slot; unallocated entries point
        #: at the dump page so fixed-shape gathers/scatters stay safe
        self.page_table = np.full((self.slots, self.pages_per_slot),
                                  DUMP_PAGE, np.int32)
        self.seq_lens = np.zeros((self.slots,), np.int32)
        self.active = np.zeros((self.slots,), bool)
        self._free_slots: List[int] = list(range(self.slots))
        self._free_pages: List[int] = list(range(1, self.pool_pages))
        self._pages_per_slot_live = [0] * self.slots
        #: slot-mapping count per physical page (the dump page stays 0)
        self._ref = np.zeros((self.pool_pages,), np.int64)
        self._root = _RadixNode(None, None, digest=kvfabric.DIGEST_SEED)
        self._by_page: Dict[int, _RadixNode] = {}
        #: host-RAM spill tier (attached by the engine when configured):
        #: the store plus the device extract/land callbacks — every call
        #: happens on the scheduler thread (the pools are donated
        #: buffers; only that thread may touch them)
        self._spill: "Optional[kvfabric.HostPageStore]" = None
        self._spill_extract: Optional[Callable[[int, bytes], bytes]] = None
        self._spill_land: Optional[Callable[[int, bytes, bytes],
                                            None]] = None
        #: leading-block digests resident ONLY in the spill tier (their
        #: HBM copy was evicted) — still advertised for affinity routing
        self._spill_leading: set = set()
        #: indexed pages with refcount 0, insertion order == LRU order
        self._retained: "OrderedDict[int, None]" = OrderedDict()
        #: pages with refcount >= 2, maintained incrementally on ref
        #: transitions — _gauges runs on the decode hot loop and must
        #: not rescan the pool
        self._shared_count = 0
        self._gauges()

    # ------------------------------------------------------------- metrics
    def _gauges(self):
        used = self.pool_pages - 1 - len(self._free_pages) \
            - len(self._retained)
        monitor.gauge("serving_decode_page_pool_used",
                      "KV-cache pages referenced by live slots",
                      labels=("model",)).set(used, model=self.name)
        monitor.gauge("serving_decode_page_pool_pages",
                      "Total allocatable KV-cache pages in the pool",
                      labels=("model",)).set(self.pool_pages - 1,
                                             model=self.name)
        monitor.gauge("serving_decode_slot_occupancy",
                      "Active decode slots (in-flight sequences)",
                      labels=("model",)).set(int(self.active.sum()),
                                             model=self.name)
        monitor.gauge("serving_decode_kv_shared_pages",
                      "KV pages currently mapped by more than one slot "
                      "(prefix sharing engaged)",
                      labels=("model",)).set(self._shared_count,
                                             model=self.name)
        monitor.gauge("serving_decode_kv_retained_pages",
                      "Released prefix pages held in the LRU retained "
                      "set for future reuse (reclaimed under pressure)",
                      labels=("model",)).set(len(self._retained),
                                             model=self.name)

    # ------------------------------------------------------ spill tier
    def attach_spill(self, store, extract_fn, land_fn):
        """Wire the host-RAM spill tier in: `store` holds demoted
        frames, `extract_fn(page, digest) -> bytes` packs one HBM page,
        `land_fn(page, payload, digest)` writes one frame back. Both
        callbacks touch the donated device pools, so every spill path
        (eviction inside an admission / ensure_page, promotion inside
        admit_prompt) must run on the scheduler thread — the same
        single-driver contract the rest of this cache already assumes."""
        self._spill = store
        self._spill_extract = extract_fn
        self._spill_land = land_fn

    def _promote_locked(self, node: _RadixNode, rest_keys: List[bytes],
                        pins: List[int]) -> List[int]:
        """Promote-on-hit: extend an HBM radix match block-by-block from
        the host spill tier. Each promoted page is landed, indexed, and
        ref-pinned (appended to `pins`; the caller unrefs after mapping
        or rollback) so the allocation of a later block can never evict
        an earlier one mid-promotion. Stops at the first absent digest,
        dry pool, or land failure — partial promotion is just a shorter
        cached prefix."""
        pages: List[int] = []
        dig = node.digest
        for key in rest_keys:
            dig = hashlib.sha256(dig + key).digest()
            if not self._spill.contains(dig):
                break
            page = self._take_page_locked()
            if page is None:
                break
            payload = self._spill.get(dig)
            ok = payload is not None
            if ok:
                try:
                    self._spill_land(page, payload, dig)
                except Exception:   # noqa: BLE001 — a corrupt/mis-
                    # shaped host frame must degrade to a cache miss
                    # (the suffix prefills normally), never fail the
                    # admission that probed it
                    log.exception(
                        "kvcache[%s]: spill promotion failed; dropping "
                        "host frame", self.name)
                    self._spill.drop(dig)
                    ok = False
            if not ok:
                self._ref[page] = 0
                self._free_pages.append(page)
                break
            child = _RadixNode(key, node, page, digest=dig)
            node.children[key] = child
            self._by_page[page] = child
            self._ref[page] = 1         # pinned until the admission
            pins.append(page)           # maps it (or rolls back)
            if node is self._root:
                self._spill_leading.discard(dig)
            monitor.counter(
                "serving_kv_spill_promotions_total",
                "KV pages promoted from the host spill tier back into "
                "the HBM pool on an admission hit",
                labels=("model",)).inc(model=self.name)
            node = child
            pages.append(page)
        return pages

    # ------------------------------------------------- page accounting
    def _unref_locked(self, page: int):
        """One slot mapping gone: route a zero-ref page to the retained
        set (still indexed — future prompts can share it) or free it."""
        if page == DUMP_PAGE:
            return
        if self._ref[page] > 0:
            if self._ref[page] == 2:
                self._shared_count -= 1
            self._ref[page] -= 1
        if self._ref[page] == 0:
            if page in self._by_page:
                # MRU on release: the prefix was just used end-to-end
                self._retained[page] = None
                self._retained.move_to_end(page)
            else:
                self._free_pages.append(page)

    def _ref_locked(self, page: int):
        self._ref[page] += 1
        if self._ref[page] == 2:
            self._shared_count += 1
        self._retained.pop(page, None)

    def _demote_locked(self, node: _RadixNode):
        """Spill one about-to-be-freed retained page to the host tier.

        ORDER IS THE CONTRACT: the host copy must be durable (put()
        returned) BEFORE the caller unindexes/frees the HBM copy —
        otherwise there is a window where the index still answers a hit
        that resolves to a freed (reusable, soon-garbage) page. The
        extract callback runs the engine's non-donating page-read
        program; a demotion failure only loses cache, never data."""
        if self._spill is None or not node.digest:
            return
        try:
            payload = self._spill_extract(node.page, node.digest)
            if self._spill.put(node.digest, payload) \
                    and node.parent is self._root:
                self._spill_leading.add(node.digest)
        except Exception:   # noqa: BLE001 — a failed demotion must
            # degrade to a plain eviction (cache loss), never crash the
            # allocation path that triggered it
            log.exception("kvcache[%s]: spill demotion failed; page %d "
                          "evicts without a host copy", self.name,
                          node.page)

    def _drop_subtree_locked(self, node: _RadixNode) -> int:
        """Unindex `node` and every descendant; retained pages demote to
        the spill tier (host copy durable first) then free, in-use pages
        merely lose future shareability. Returns the number of cache
        entries evicted."""
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
        stack, evicted = [node], 0
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            n.children = {}
            if self._by_page.get(n.page) is n:
                if n.page in self._retained:
                    # durable host copy FIRST — only then unindex + free
                    self._demote_locked(n)
                    del self._by_page[n.page]
                    del self._retained[n.page]
                    self._free_pages.append(n.page)
                else:
                    del self._by_page[n.page]
                evicted += 1
        return evicted

    def _evict_locked(self) -> bool:
        """Reclaim one LRU retained entry (leaf-preferred: drop a chain's
        tail before its root so the hot head of a prefix survives
        longest). Returns False when nothing is evictable."""
        victim = None
        # oldest first; skipped entries are internal nodes of a chain
        # released root-before-tail, so the first leaf surfaces within
        # one chain depth (<= pages_per_slot probes), not O(retained)
        for page in self._retained:
            node = self._by_page.get(page)
            if node is not None and not node.children:
                victim = page
                break
        if victim is None:                          # every retained node
            victim = next(iter(self._retained), None)   # has in-use kids
        if victim is None:
            return False
        evicted = self._drop_subtree_locked(self._by_page[victim])
        monitor.counter(
            "serving_decode_kv_cache_evictions_total",
            "Prefix-cache entries evicted under pool pressure (LRU over "
            "the retained set; a subtree goes with its root)",
            labels=("model",)).inc(evicted, model=self.name)
        return True

    def _take_page_locked(self) -> Optional[int]:
        """One fresh page: free list first, then LRU eviction of the
        retained set; None when the pool is genuinely dry."""
        while True:
            if self._free_pages:
                return self._free_pages.pop()
            if not self._retained or not self._evict_locked():
                return None

    # ------------------------------------------------------ radix walking
    def _blocks(self, tokens) -> Tuple[np.ndarray, List[bytes]]:
        """Canonical (flat, contiguous int32) token view + the trie key
        of every FULL page-aligned block. The ONE definition indexing
        and lookup share: the trie matches raw token bytes, so a dtype
        or layout tweak applied to only one side would silently zero
        the hit rate instead of erroring."""
        tokens = np.ascontiguousarray(np.asarray(tokens, np.int32)
                                      .reshape(-1))
        ps = self.page_size
        keys = [tokens[i * ps:(i + 1) * ps].tobytes()
                for i in range(int(tokens.size) // ps)]
        return tokens, keys

    def _walk_locked(self, keys: List[bytes]
                     ) -> Tuple[_RadixNode, List[int]]:
        """Longest indexed prefix of `keys`: (deepest matched node, its
        canonical pages root-to-match)."""
        node, pages = self._root, []
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            pages.append(child.page)
            node = child
        return node, pages

    # ----------------------------------------------------------- lifecycle
    def pages_for(self, length: int) -> int:
        """Physical pages needed to hold `length` cached positions."""
        return (int(length) + self.page_size - 1) // self.page_size

    def _check_capacity(self, prompt_len: int):
        if self.pages_for(prompt_len) > self.pages_per_slot:
            raise ValueError(
                f"kvcache[{self.name}]: {prompt_len} cached positions "
                f"exceed per-slot capacity ({self.max_context}); the "
                "caller must validate prompt length first")

    def admit(self, prompt_len: int) -> Optional[int]:
        """Token-less admission: claim a slot + fresh pages covering the
        prompt, no sharing and no later retention. None when either
        resource is exhausted (the join waits — never an error)."""
        self._check_capacity(prompt_len)
        need = self.pages_for(prompt_len)
        with self._lock:
            slot = self._admit_locked(prompt_len, [], need)
            return None if slot is None else slot

    def admit_prompt(self, tokens) -> Optional[AdmitInfo]:
        """Token-aware admission: resolve the longest cached prefix of
        full page-aligned blocks, map those pages read-shared, allocate
        fresh pages for the rest. None when out of slots/pages.

        A fully-cached page-aligned prompt still must recompute its last
        token (first-token logits come from prefill), and that write
        would diverge the last shared page — the returned AdmitInfo then
        carries a (cow_src, cow_dst) copy-on-write pair the engine
        resolves on-device before the suffix prefill."""
        tokens, keys = self._blocks(tokens)
        prompt_len = int(tokens.size)
        if prompt_len < 1:
            raise ValueError("admit_prompt needs at least one token")
        self._check_capacity(prompt_len)
        need = self.pages_for(prompt_len)
        ps = self.page_size
        with self._lock:
            pins: List[int] = []
            if self.prefix_cache:
                deepest, matched = self._walk_locked(keys)
                if self._spill is not None and len(matched) < len(keys):
                    # the HBM walk stopped short: probe the host tier.
                    # Matched pages get ref-pinned first — promotion
                    # allocates pages, allocation can evict, and an
                    # eviction must never reach a page this admission
                    # is about to map read-shared
                    for p in matched:
                        self._ref_locked(p)
                        pins.append(p)
                    promoted = self._promote_locked(
                        deepest, keys[len(matched):], pins)
                    hit = len(promoted) > 0
                    monitor.counter(
                        "serving_kv_spill_hits_total",
                        "Admissions whose HBM-missed prefix blocks were "
                        "served (>= one page) from the host spill tier",
                        labels=("model",)).inc(int(hit), model=self.name)
                    monitor.counter(
                        "serving_kv_spill_misses_total",
                        "Admissions that probed the host spill tier for "
                        "their uncached blocks and found none",
                        labels=("model",)).inc(int(not hit),
                                               model=self.name)
                    matched = matched + promoted
            else:
                matched = []
            cached_len = len(matched) * ps
            cow_src = None
            if cached_len and cached_len >= prompt_len:
                # fully covered: leave the last token to prefill; its
                # write diverges the final shared page -> copy-on-write
                cached_len = prompt_len - 1
                cow_src = matched[-1]
                shared = matched[:-1]
            else:
                shared = matched
            slot = self._admit_locked(prompt_len, shared, need,
                                      pin=cow_src)
            for p in pins:
                self._unref_locked(p)
            if slot is None:
                self._gauges()
                return None
            cow_dst = None if cow_src is None \
                else int(self.page_table[slot, len(shared)])
            if self.prefix_cache:
                hit = cached_len > 0
                monitor.counter(
                    "serving_decode_kv_cache_hits_total",
                    "Admissions that reused a cached prompt prefix "
                    "(>= one full page of KV skipped prefill)",
                    labels=("model",)).inc(int(hit), model=self.name)
                monitor.counter(
                    "serving_decode_kv_cache_misses_total",
                    "Admissions with no cached prefix (full prefill)",
                    labels=("model",)).inc(int(not hit), model=self.name)
            return AdmitInfo(slot, cached_len, cow_src, cow_dst)

    def _admit_locked(self, prompt_len: int, shared: Sequence[int],
                      need: int, pin: Optional[int] = None
                      ) -> Optional[int]:
        """Map `shared` read-shared + allocate the remaining fresh pages
        into a free slot; all-or-nothing (rolls back on pool pressure).
        `pin` ref-pins an extra page (the COW source) so eviction cannot
        reach it between admission and the on-device copy."""
        if not self._free_slots:
            return None
        for p in shared:
            self._ref_locked(p)
        if pin is not None:
            self._ref_locked(pin)
        fresh: List[int] = []
        for _ in range(need - len(shared)):
            p = self._take_page_locked()
            if p is None:
                for q in fresh:
                    self._ref[q] = 0
                    self._free_pages.append(q)
                for q in shared:
                    self._unref_locked(q)
                if pin is not None:
                    self._unref_locked(pin)
                return None
            self._ref[p] = 1
            fresh.append(p)
        slot = self._free_slots.pop()
        self.page_table[slot, :] = DUMP_PAGE
        for i, p in enumerate(list(shared) + fresh):
            self.page_table[slot, i] = p
        self._pages_per_slot_live[slot] = need
        self.seq_lens[slot] = prompt_len
        self.active[slot] = True
        self._gauges()
        return slot

    def unref_page(self, page: int):
        """Drop a temporary pin (the engine calls this once the COW copy
        has executed; the source page goes back to shared/retained
        accounting)."""
        with self._lock:
            self._unref_locked(page)
            self._gauges()

    def register_prefix(self, slot: int, tokens):
        """Index this slot's full prompt blocks (prefill is complete —
        every mapped prompt page now holds final K/V). Blocks already
        indexed keep their existing canonical page; a racing duplicate
        prompt simply fails to index and frees on release."""
        if not self.prefix_cache:
            return
        _, keys = self._blocks(tokens)
        with self._lock:
            node = self._root
            for i, key in enumerate(keys):
                child = node.children.get(key)
                if child is None:
                    page = int(self.page_table[slot, i])
                    if page == DUMP_PAGE or page in self._by_page:
                        return          # defensive: never index the dump
                    child = _RadixNode(
                        key, node, page,
                        digest=hashlib.sha256(node.digest + key).digest())
                    node.children[key] = child
                    self._by_page[page] = child
                node = child

    def adopt_pages(self, tokens, land_fn) -> int:
        """Land externally-computed KV pages (a disaggregated-prefill
        shipment) straight into the radix index as zero-ref retained
        pages: `land_fn(i, page)` writes block i's frame into physical
        page `page` (raising on corruption — the page is freed and the
        error surfaces cleanly). Blocks already indexed are skipped, so
        a duplicate shipment is idempotent. The NEXT `admit_prompt` of
        this prefix then hits exactly like a locally-prefilled one —
        which is what makes remote prefill bitwise the local path.
        Returns the number of pages adopted."""
        if not self.prefix_cache:
            return 0
        tokens, keys = self._blocks(tokens)
        if not keys:
            return 0
        adopted = 0
        pins: List[int] = []
        with self._lock:
            node = self._root
            try:
                for i, key in enumerate(keys):
                    child = node.children.get(key)
                    if child is not None:
                        # pin the existing chain so a later block's
                        # allocation cannot evict it mid-adoption
                        self._ref_locked(child.page)
                        pins.append(child.page)
                        node = child
                        continue
                    page = self._take_page_locked()
                    if page is None:
                        break           # pool dry: partial adoption is
                        # just a shorter cached prefix
                    try:
                        land_fn(i, page)
                    except Exception:
                        self._ref[page] = 0
                        self._free_pages.append(page)
                        raise
                    child = _RadixNode(
                        key, node, page,
                        digest=hashlib.sha256(node.digest + key)
                        .digest())
                    node.children[key] = child
                    self._by_page[page] = child
                    self._ref[page] = 1
                    pins.append(page)
                    adopted += 1
                    node = child
            finally:
                for p in pins:
                    self._unref_locked(p)
                self._gauges()
        return adopted

    def ownership_digests(self, limit: int = 64) -> List[str]:
        """Leading-block (depth-1) prefix digests this cache can serve
        hot — HBM-indexed roots plus host-spilled ones — as short hex
        handles. Published on /readyz heartbeats; the router steers
        same-prefix streams to the advertising replica."""
        with self._lock:
            out = [c.digest.hex()[:16]
                   for c in self._root.children.values() if c.digest]
            for d in self._spill_leading:
                h = d.hex()[:16]
                if h not in out:
                    out.append(h)
            return out[:max(0, int(limit))]

    def ensure_page(self, slot: int) -> bool:
        """Guarantee a physical page exists for this slot's NEXT position
        (``seq_lens[slot]``). Returns False when the pool is dry — the
        caller masks the slot out of this step and retries later."""
        with self._lock:
            pos = int(self.seq_lens[slot])
            if pos >= self.max_context:
                return False            # context cap; caller finishes it
            idx = pos // self.page_size
            if idx < self._pages_per_slot_live[slot]:
                return True
            page = self._take_page_locked()
            if page is None:
                monitor.counter(
                    "serving_decode_page_stalls_total",
                    "Decode steps a slot sat out waiting for a free "
                    "KV page (pool oversubscribed)",
                    labels=("model",)).inc(model=self.name)
                return False
            self._ref[page] = 1
            self.page_table[slot, idx] = page
            self._pages_per_slot_live[slot] = idx + 1
            self._gauges()
            return True

    def ensure_capacity(self, slot: int, n: int) -> bool:
        """Guarantee physical pages for this slot's next ``n`` positions
        (``seq_lens[slot] .. seq_lens[slot]+n-1``) — the write span of a
        speculative draft/verify burst. Returns False when the context
        cap or a dry pool blocks any of them; pages allocated before the
        pool ran dry stay mapped (slot-owned, reused on retry/release)."""
        with self._lock:
            pos = int(self.seq_lens[slot])
            if pos + n > self.max_context:
                return False            # burst would overrun the context
            if n < 1:
                return True
            need_idx = (pos + n - 1) // self.page_size
            allocated = False
            while self._pages_per_slot_live[slot] <= need_idx:
                page = self._take_page_locked()
                if page is None:
                    monitor.counter(
                        "serving_decode_page_stalls_total",
                        "Decode steps a slot sat out waiting for a free "
                        "KV page (pool oversubscribed)",
                        labels=("model",)).inc(model=self.name)
                    if allocated:
                        self._gauges()
                    return False
                idx = self._pages_per_slot_live[slot]
                self._ref[page] = 1
                self.page_table[slot, idx] = page
                self._pages_per_slot_live[slot] = idx + 1
                allocated = True
            if allocated:
                self._gauges()
            return True

    def advance(self, slot: int):
        """One token appended at ``seq_lens[slot]`` by the decode step."""
        self.seq_lens[slot] += 1

    def release(self, slot: int):
        """Sequence finished: unreference its pages (indexed ones join
        the retained set, the rest free) and return the slot."""
        with self._lock:
            if not self.active[slot]:
                return
            n = self._pages_per_slot_live[slot]
            for p in self.page_table[slot, :n]:
                self._unref_locked(int(p))
            self.page_table[slot, :] = DUMP_PAGE
            self._pages_per_slot_live[slot] = 0
            self.seq_lens[slot] = 0
            self.active[slot] = False
            self._free_slots.append(slot)
            self._gauges()

    # -------------------------------------------------------------- status
    def active_slots(self) -> List[int]:
        return [i for i in range(self.slots) if self.active[i]]

    def free_pages(self) -> int:
        """Allocatable pages: truly free + retained (the retained set is
        reclaimable cache, not working memory)."""
        with self._lock:
            return len(self._free_pages) + len(self._retained)

    def retained_pages(self) -> int:
        with self._lock:
            return len(self._retained)

    def ref_count(self, page: int) -> int:
        with self._lock:
            return int(self._ref[page])

    def cached_prefix_len(self, tokens) -> int:
        """Longest currently-indexed prefix (full blocks) of `tokens` in
        tokens — a read-only probe, no LRU touch."""
        _, keys = self._blocks(tokens)
        with self._lock:
            return len(self._walk_locked(keys)[1]) * self.page_size

    def utilization(self) -> float:
        total = self.pool_pages - 1
        return (total - self.free_pages()) / max(1, total)

    def describe(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "active_slots": int(self.active.sum()),
                "page_size": self.page_size,
                "max_context": self.max_context,
                "pool_pages": self.pool_pages - 1,
                "pages_used": (self.pool_pages - 1 - len(self._free_pages)
                               - len(self._retained)),
                "prefix_cache": self.prefix_cache,
                "retained_pages": len(self._retained),
                "shared_pages": self._shared_count,
                "spill": None if self._spill is None
                else self._spill.describe(),
            }


# --------------------------------------------------------------------------
# Device-side helpers (pure; used INSIDE the jitted prefill/decode programs)
# --------------------------------------------------------------------------
def append_token_kv(kpool, vpool, layer: int, k, v, phys, off):
    """Scatter one new (key, value) row per slot into the pools.

    kpool/vpool: (L, P, page_size, H, D); k/v: (S, H, D); phys/off: (S,)
    physical page id and in-page offset per slot (inactive slots steered
    to DUMP_PAGE by the caller). Returns the updated pools."""
    kpool = kpool.at[layer, phys, off].set(k)
    vpool = vpool.at[layer, phys, off].set(v)
    return kpool, vpool


def write_prompt_kv(kpool, vpool, layer: int, k_seq, v_seq, page_row,
                    page_size: int):
    """Blast a prefilled prompt's (key, value) rows into this slot's pages.

    k_seq/v_seq: (T, H, D) with T a static multiple of page_size (the
    prefill bucket); page_row: (pages_per_slot,) — entries past the
    prompt's allocation point at DUMP_PAGE, so the fixed-count scatter
    can't touch another sequence's pages. Returns the updated pools."""
    t, h, d = k_seq.shape
    npages = t // page_size
    kp = k_seq.reshape(npages, page_size, h, d)
    vp = v_seq.reshape(npages, page_size, h, d)
    kpool = kpool.at[layer, page_row[:npages]].set(kp)
    vpool = vpool.at[layer, page_row[:npages]].set(vp)
    return kpool, vpool


def write_chunk_kv(kpool, vpool, layer: int, k_seq, v_seq, phys, off):
    """Scatter a prefill *chunk*'s (key, value) rows by absolute position.

    Unlike `write_prompt_kv` this makes no page-alignment assumption —
    the chunk may start mid-page (the COW divergence recompute does).
    k_seq/v_seq: (T, H, D); phys/off: (T,) physical page and in-page
    offset per row, with invalid (padding / past-end) rows steered to
    DUMP_PAGE by the caller. Returns the updated pools."""
    kpool = kpool.at[layer, phys, off].set(k_seq)
    vpool = vpool.at[layer, phys, off].set(v_seq)
    return kpool, vpool


def gather_kv(kpool, vpool, layer: int, page_table, max_context: int):
    """Page-table gather back to dense per-slot key/value sequences.

    page_table: (S, pages_per_slot) int32. Returns (keys, values) shaped
    (S, max_context, H, D); positions past a slot's live length hold
    stale/dump garbage — the attention mask (``pos <= seq_len``) is the
    single source of validity."""
    s = page_table.shape[0]
    h, d = kpool.shape[-2], kpool.shape[-1]
    keys = kpool[layer][page_table].reshape(s, max_context, h, d)
    vals = vpool[layer][page_table].reshape(s, max_context, h, d)
    return keys, vals


def copy_page(kpool, vpool, src, dst):
    """Copy one physical page across every layer (the COW divergence).
    src/dst are traced int32 scalars, so ONE compiled program serves
    every copy-on-write regardless of which pages diverge."""
    kpool = kpool.at[:, dst].set(kpool[:, src])
    vpool = vpool.at[:, dst].set(vpool[:, src])
    return kpool, vpool


def default_prefill_buckets(page_size: int, max_context: int
                            ) -> Sequence[int]:
    """Prefill bucket ladder: page-aligned, geometric (x4), capped by and
    always including max_context — same philosophy as the predict
    batcher's 1/8/32/128 ladder (few compiles, bounded padding waste)."""
    buckets, b = [], page_size
    while b < max_context:
        buckets.append(b)
        b *= 4
    buckets.append(max_context)
    return tuple(sorted(set(buckets)))
