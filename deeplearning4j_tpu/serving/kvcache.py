"""Paged KV cache — fixed-shape attention memory for continuous batching.

Autoregressive decode is memory-shaped like virtual memory, not like a
tensor: each in-flight sequence grows one (key, value) row per layer per
token, sequences join and leave at arbitrary times, and naive per-sequence
contiguous buffers either fragment HBM or force worst-case preallocation
per request. The PagedAttention design (Kwon et al., SOSP'23) maps the OS
page table onto the KV cache, and this module is that design sized for the
AOT-warm serving contract of `serving/batcher.py`:

- **One physical pool, fixed at load time.** Per layer, keys and values
  live in ``(L, P, page_size, H, D)`` arrays allocated once when the
  servable loads. Every compiled program sees these exact shapes forever —
  no growth, no reallocation, no shape-driven recompiles.
- **Per-slot page tables.** Each decode *slot* (a fixed position in the
  running batch) owns a row of ``max_context // page_size`` physical page
  ids. Logical position ``t`` of a sequence lives at physical page
  ``table[t // page_size]``, offset ``t % page_size`` — pages are
  allocated on demand as the sequence crosses page boundaries and returned
  the moment the sequence finishes.
- **The dump page.** Physical page 0 is never allocated. Fixed-shape
  scatter writes from *inactive* slots and from prompt-padding positions
  are steered to page 0, so the compiled step never needs a dynamic shape
  or a conditional write — garbage goes to a page nobody reads.

The host side (`KVCacheState`) is plain numpy + a free list: allocation
decisions happen between compiled steps, and the page table crosses to the
device as a small int32 operand each step. The device side is two pure
gather/scatter helpers used inside the jitted prefill/decode programs.
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu import monitor

#: physical page 0 — the write sink for inactive slots / padded positions.
DUMP_PAGE = 0


class PagePoolExhaustedError(RuntimeError):
    """No free physical page for a sequence that must grow (the caller
    stalls the slot or sheds the join; this never crashes a step)."""


class KVCacheState:
    """Host-side bookkeeping for one engine's paged KV cache.

    Owns the slot free list, the physical-page free list and the numpy
    page table / sequence lengths mirrored into every compiled step.
    Thread-safe (admissions come from the scheduler thread, releases can
    race drains), but the expected driver is a single scheduler loop.
    """

    def __init__(self, slots: int, page_size: int, max_context: int,
                 pool_pages: Optional[int] = None, name: str = "lm"):
        if page_size < 1 or slots < 1:
            raise ValueError(f"slots/page_size must be >= 1 "
                             f"(got {slots}/{page_size})")
        if max_context % page_size:
            raise ValueError(
                f"max_context {max_context} must be a multiple of "
                f"page_size {page_size}")
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.max_context = int(max_context)
        self.pages_per_slot = self.max_context // self.page_size
        # +1: the dump page. The default pool is NOT oversubscribed (every
        # slot can reach max_context); size it smaller to oversubscribe
        # memory against typical sequence lengths — joins then wait on the
        # free list when the pool runs dry.
        self.pool_pages = int(pool_pages) if pool_pages is not None \
            else 1 + self.slots * self.pages_per_slot
        if self.pool_pages < 1 + self.pages_per_slot:
            raise ValueError(
                f"pool_pages {self.pool_pages} cannot hold even one "
                f"max-context sequence ({1 + self.pages_per_slot} needed)")
        self.name = name
        self._lock = threading.Lock()
        #: logical->physical page map per slot; unallocated entries point
        #: at the dump page so fixed-shape gathers/scatters stay safe
        self.page_table = np.full((self.slots, self.pages_per_slot),
                                  DUMP_PAGE, np.int32)
        self.seq_lens = np.zeros((self.slots,), np.int32)
        self.active = np.zeros((self.slots,), bool)
        self._free_slots: List[int] = list(range(self.slots))
        self._free_pages: List[int] = list(range(1, self.pool_pages))
        self._pages_per_slot_live = [0] * self.slots
        self._gauges()

    # ------------------------------------------------------------- metrics
    def _gauges(self):
        used = self.pool_pages - 1 - len(self._free_pages)
        monitor.gauge("serving_decode_page_pool_used",
                      "Allocated KV-cache pages (of the fixed pool)",
                      labels=("model",)).set(used, model=self.name)
        monitor.gauge("serving_decode_page_pool_pages",
                      "Total allocatable KV-cache pages in the pool",
                      labels=("model",)).set(self.pool_pages - 1,
                                             model=self.name)
        monitor.gauge("serving_decode_slot_occupancy",
                      "Active decode slots (in-flight sequences)",
                      labels=("model",)).set(int(self.active.sum()),
                                             model=self.name)

    # ----------------------------------------------------------- lifecycle
    def pages_for(self, length: int) -> int:
        """Physical pages needed to hold `length` cached positions."""
        return (int(length) + self.page_size - 1) // self.page_size

    def admit(self, prompt_len: int) -> Optional[int]:
        """Claim a slot + the pages covering the prompt; None when either
        resource is exhausted (the join waits — never an error)."""
        need = self.pages_for(prompt_len)
        if need > self.pages_per_slot:
            raise ValueError(
                f"kvcache[{self.name}]: {prompt_len} cached positions "
                f"exceed per-slot capacity ({self.max_context}); the "
                "caller must validate prompt length first")
        with self._lock:
            if not self._free_slots or len(self._free_pages) < need:
                return None
            slot = self._free_slots.pop()
            pages = [self._free_pages.pop() for _ in range(need)]
            self.page_table[slot, :] = DUMP_PAGE
            self.page_table[slot, :need] = pages
            self._pages_per_slot_live[slot] = need
            self.seq_lens[slot] = prompt_len
            self.active[slot] = True
            self._gauges()
            return slot

    def ensure_page(self, slot: int) -> bool:
        """Guarantee a physical page exists for this slot's NEXT position
        (``seq_lens[slot]``). Returns False when the pool is dry — the
        caller masks the slot out of this step and retries later."""
        with self._lock:
            pos = int(self.seq_lens[slot])
            if pos >= self.max_context:
                return False            # context cap; caller finishes it
            idx = pos // self.page_size
            if idx < self._pages_per_slot_live[slot]:
                return True
            if not self._free_pages:
                monitor.counter(
                    "serving_decode_page_stalls_total",
                    "Decode steps a slot sat out waiting for a free "
                    "KV page (pool oversubscribed)",
                    labels=("model",)).inc(model=self.name)
                return False
            self.page_table[slot, idx] = self._free_pages.pop()
            self._pages_per_slot_live[slot] = idx + 1
            self._gauges()
            return True

    def advance(self, slot: int):
        """One token appended at ``seq_lens[slot]`` by the decode step."""
        self.seq_lens[slot] += 1

    def release(self, slot: int):
        """Sequence finished: return its pages and the slot."""
        with self._lock:
            if not self.active[slot]:
                return
            n = self._pages_per_slot_live[slot]
            self._free_pages.extend(int(p) for p in self.page_table[slot, :n])
            self.page_table[slot, :] = DUMP_PAGE
            self._pages_per_slot_live[slot] = 0
            self.seq_lens[slot] = 0
            self.active[slot] = False
            self._free_slots.append(slot)
            self._gauges()

    # -------------------------------------------------------------- status
    def active_slots(self) -> List[int]:
        return [i for i in range(self.slots) if self.active[i]]

    def free_pages(self) -> int:
        with self._lock:
            return len(self._free_pages)

    def utilization(self) -> float:
        total = self.pool_pages - 1
        return (total - self.free_pages()) / max(1, total)

    def describe(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "active_slots": int(self.active.sum()),
                "page_size": self.page_size,
                "max_context": self.max_context,
                "pool_pages": self.pool_pages - 1,
                "pages_used": self.pool_pages - 1 - len(self._free_pages),
            }


# --------------------------------------------------------------------------
# Device-side helpers (pure; used INSIDE the jitted prefill/decode programs)
# --------------------------------------------------------------------------
def append_token_kv(kpool, vpool, layer: int, k, v, phys, off):
    """Scatter one new (key, value) row per slot into the pools.

    kpool/vpool: (L, P, page_size, H, D); k/v: (S, H, D); phys/off: (S,)
    physical page id and in-page offset per slot (inactive slots steered
    to DUMP_PAGE by the caller). Returns the updated pools."""
    kpool = kpool.at[layer, phys, off].set(k)
    vpool = vpool.at[layer, phys, off].set(v)
    return kpool, vpool


def write_prompt_kv(kpool, vpool, layer: int, k_seq, v_seq, page_row,
                    page_size: int):
    """Blast a prefilled prompt's (key, value) rows into this slot's pages.

    k_seq/v_seq: (T, H, D) with T a static multiple of page_size (the
    prefill bucket); page_row: (pages_per_slot,) — entries past the
    prompt's allocation point at DUMP_PAGE, so the fixed-count scatter
    can't touch another sequence's pages. Returns the updated pools."""
    t, h, d = k_seq.shape
    npages = t // page_size
    kp = k_seq.reshape(npages, page_size, h, d)
    vp = v_seq.reshape(npages, page_size, h, d)
    kpool = kpool.at[layer, page_row[:npages]].set(kp)
    vpool = vpool.at[layer, page_row[:npages]].set(vp)
    return kpool, vpool


def gather_kv(kpool, vpool, layer: int, page_table, max_context: int):
    """Page-table gather back to dense per-slot key/value sequences.

    page_table: (S, pages_per_slot) int32. Returns (keys, values) shaped
    (S, max_context, H, D); positions past a slot's live length hold
    stale/dump garbage — the attention mask (``pos <= seq_len``) is the
    single source of validity."""
    s = page_table.shape[0]
    h, d = kpool.shape[-2], kpool.shape[-1]
    keys = kpool[layer][page_table].reshape(s, max_context, h, d)
    vals = vpool[layer][page_table].reshape(s, max_context, h, d)
    return keys, vals


def default_prefill_buckets(page_size: int, max_context: int
                            ) -> Sequence[int]:
    """Prefill bucket ladder: page-aligned, geometric (x4), capped by and
    always including max_context — same philosophy as the predict
    batcher's 1/8/32/128 ladder (few compiles, bounded padding waste)."""
    buckets, b = [], page_size
    while b < max_context:
        buckets.append(b)
        b *= 4
    buckets.append(max_context)
    return tuple(sorted(set(buckets)))
