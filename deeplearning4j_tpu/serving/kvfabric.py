"""Tiered KV fabric — host-RAM spill tier + page serialization.

The paged prefix cache (`serving/kvcache.py`) is capped at one device's
page pool: a hot system prompt survives release only until HBM pressure
evicts it, and it never survives a process boundary at all. This module
is the fleet-infrastructure answer, in two parts:

- **A host-RAM page store** (`HostPageStore`): a pinned shared-memory
  slab of fixed-size frames — the PR-7 ETL-ring substrate reused for
  serving. Zero-ref retained pages *demote* here instead of being freed
  under pool pressure, and a later admission *promotes* them back into
  HBM — the effective prefix cache is host-RAM sized, not HBM sized.
- **A bitwise, version-tagged wire format** (`pack_page`/`unpack_page`,
  `pack_transfer`/`unpack_transfer`): length-prefixed frames with a
  sha256 integrity trailer, used both as the spill tier's at-rest format
  and as the prefill→decode transfer format of the disaggregated
  serving path. A truncated or corrupt frame raises `FrameError` — a
  clean, catchable rejection, never a scheduler-thread death.

Page identity is the *prefix path*, not the block alone: the same token
block under two different prefixes holds different K/V. Keys are chained
digests — ``d_i = sha256(d_{i-1} + block_bytes)`` seeded by a format
constant — computed from the exact token bytes the radix trie indexes
(`KVCacheState._blocks`), so a spill hit can never alias across
prefixes. The leading-block digest (depth 1) doubles as the router's
prefix-affinity ownership unit.
"""
from __future__ import annotations

import hashlib
import json
import struct
import weakref
from collections import OrderedDict
from multiprocessing import shared_memory
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.util.locks import DiagnosedLock

try:                                    # jax's numpy dtype extensions
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:                       # noqa: BLE001 — optional: the
    # wire format degrades to the dtypes numpy knows natively
    ml_dtypes = None
    _BF16 = None

#: chained-digest seed — part of the wire format; bump with VERSION
DIGEST_SEED = b"tpu-dl4j/kvfabric/v1"
#: per-page frame magic + format version
PAGE_MAGIC = b"KVPG"
#: multi-page transfer envelope magic + format version
TRANSFER_MAGIC = b"KVXF"
VERSION = 1

_PAGE_HDR = struct.Struct("<4sHI")      # magic, version, json header len
_U64 = struct.Struct("<Q")
_SHA_LEN = 32


class FrameError(ValueError):
    """A serialized KV frame failed validation (bad magic/version,
    truncation, length overrun, digest mismatch, or geometry that does
    not fit the receiving pool). Always catchable — the deserializer
    never lets malformed bytes crash the caller's thread."""


def _dtype_of(name: str) -> np.dtype:
    if name == "bfloat16":
        if _BF16 is None:
            raise FrameError("frame dtype bfloat16 needs ml_dtypes, "
                             "which is unavailable in this process")
        return _BF16
    try:
        return np.dtype(name)
    except TypeError as e:
        raise FrameError(f"frame names unknown dtype {name!r}") from e


def chain_digests(keys: Sequence[bytes],
                  seed: bytes = DIGEST_SEED) -> List[bytes]:
    """Chained path digests of a block-key sequence: ``d_i =
    sha256(d_{i-1} + key_i)`` with ``d_-1 = seed``. The i-th digest
    identifies block i *in the context of every block before it*."""
    digs, d = [], seed
    for key in keys:
        d = hashlib.sha256(d + key).digest()
        digs.append(d)
    return digs


def leading_digest(tokens, page_size: int) -> Optional[bytes]:
    """Digest of the first full page-aligned block of `tokens` (the
    prefix-affinity ownership unit), or None for sub-page prompts.
    Byte-for-byte the kvcache trie's block key convention."""
    t = np.ascontiguousarray(np.asarray(tokens, np.int32).reshape(-1))
    if int(t.size) < page_size:
        return None
    return hashlib.sha256(
        DIGEST_SEED + t[:page_size].tobytes()).digest()


# ==========================================================================
# Per-page frame: header JSON + raw K bytes + raw V bytes + sha256 trailer
# ==========================================================================
def pack_page(k: np.ndarray, v: np.ndarray, digest: bytes) -> bytes:
    """Serialize one physical page's (K, V) — each shaped
    ``(n_layers, page_size, heads, head_dim)`` — into a self-describing,
    self-verifying frame. Bitwise: the receiver reconstructs the exact
    array bytes, any dtype (f32 / bf16 / int8)."""
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    hdr = json.dumps({
        "v": VERSION,
        "shape": list(k.shape),
        "kdtype": str(k.dtype),
        "vdtype": str(v.dtype),
        "digest": digest.hex(),
    }, separators=(",", ":")).encode()
    kb, vb = k.tobytes(), v.tobytes()
    body = (_PAGE_HDR.pack(PAGE_MAGIC, VERSION, len(hdr)) + hdr
            + _U64.pack(len(kb)) + kb + _U64.pack(len(vb)) + vb)
    return body + hashlib.sha256(body).digest()


def unpack_page(buf: bytes, expect_digest: Optional[bytes] = None
                ) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Parse + verify one `pack_page` frame -> (k, v, header). Raises
    FrameError on any malformation; arrays are bitwise the packed ones."""
    if len(buf) < _PAGE_HDR.size + _SHA_LEN:
        raise FrameError(f"page frame truncated ({len(buf)} bytes)")
    magic, ver, hlen = _PAGE_HDR.unpack_from(buf, 0)
    if magic != PAGE_MAGIC:
        raise FrameError(f"bad page-frame magic {magic!r}")
    if ver != VERSION:
        raise FrameError(f"page-frame version {ver} unsupported "
                         f"(this build speaks {VERSION})")
    body, trailer = buf[:-_SHA_LEN], buf[-_SHA_LEN:]
    if hashlib.sha256(body).digest() != trailer:
        raise FrameError("page-frame sha256 mismatch (corrupt transfer)")
    off = _PAGE_HDR.size
    if off + hlen > len(body):
        raise FrameError("page-frame header overruns the frame")
    try:
        hdr = json.loads(body[off:off + hlen])
    except ValueError as e:
        raise FrameError(f"page-frame header is not JSON: {e}") from e
    off += hlen
    arrays = []
    for dt_name in (hdr.get("kdtype"), hdr.get("vdtype")):
        if off + _U64.size > len(body):
            raise FrameError("page frame truncated inside a length prefix")
        (n,) = _U64.unpack_from(body, off)
        off += _U64.size
        if off + n > len(body):
            raise FrameError(
                f"page frame declares {n} payload bytes but only "
                f"{len(body) - off} remain")
        dt = _dtype_of(str(dt_name))
        shape = tuple(int(s) for s in hdr.get("shape", ()))
        if int(np.prod(shape)) * dt.itemsize != n:
            raise FrameError(
                f"payload length {n} does not match shape {shape} of "
                f"dtype {dt}")
        arrays.append(np.frombuffer(body, dtype=dt, count=n // dt.itemsize,
                                    offset=off).reshape(shape))
        off += n
    if off != len(body):
        raise FrameError(f"{len(body) - off} trailing bytes after the "
                         "page payload")
    if expect_digest is not None and hdr.get("digest") \
            != expect_digest.hex():
        raise FrameError("page frame carries digest "
                         f"{hdr.get('digest')!r}, expected "
                         f"{expect_digest.hex()!r} (prefix-path mismatch)")
    return arrays[0], arrays[1], hdr


# ==========================================================================
# Multi-page transfer envelope (the prefill -> decode shipment)
# ==========================================================================
def pack_transfer(tokens, frames: Sequence[bytes],
                  page_size: int) -> bytes:
    """Envelope a page-aligned token prefix + its per-page frames into
    one length-prefixed shipment (header+tokens integrity-sealed; each
    frame self-verifies via its own trailer)."""
    t = np.ascontiguousarray(np.asarray(tokens, np.int32).reshape(-1))
    if int(t.size) % page_size or int(t.size) // page_size != len(frames):
        raise ValueError(
            f"transfer needs page-aligned tokens matching the frame "
            f"count (got {t.size} tokens / {len(frames)} frames of "
            f"page_size {page_size})")
    hdr = json.dumps({"v": VERSION, "page_size": int(page_size),
                      "n_tokens": int(t.size), "n_frames": len(frames)},
                     separators=(",", ":")).encode()
    tb = t.tobytes()
    head = (_PAGE_HDR.pack(TRANSFER_MAGIC, VERSION, len(hdr)) + hdr
            + _U64.pack(len(tb)) + tb)
    out = [head, hashlib.sha256(head).digest()]
    for fr in frames:
        out.append(_U64.pack(len(fr)))
        out.append(fr)
    return b"".join(out)


def check_frame(buf: bytes):
    """Cheap integrity gate on one sealed page frame: magic, version and
    the sha256 trailer — no array materialization. Raises FrameError.
    `unpack_transfer` runs this over every frame so a corrupt shipment
    is rejected at the wire, before any of it reaches the scheduler
    thread (land-side `unpack_page` still re-verifies in full)."""
    if len(buf) < _PAGE_HDR.size + _SHA_LEN:
        raise FrameError(f"page frame truncated ({len(buf)} bytes)")
    magic, ver, _hlen = _PAGE_HDR.unpack_from(buf, 0)
    if magic != PAGE_MAGIC:
        raise FrameError(f"bad page-frame magic {magic!r}")
    if ver != VERSION:
        raise FrameError(f"page-frame version {ver} unsupported "
                         f"(this build speaks {VERSION})")
    if hashlib.sha256(buf[:-_SHA_LEN]).digest() != buf[-_SHA_LEN:]:
        raise FrameError("page-frame sha256 mismatch (corrupt transfer)")


def unpack_transfer(buf: bytes) -> Tuple[np.ndarray, List[bytes], dict]:
    """Parse a `pack_transfer` shipment -> (tokens, frames, header).
    FrameError on truncation/corruption anywhere in the envelope OR in
    any sealed frame (each frame's sha trailer is checked here, so a
    flipped byte is caught at the wire even if the receiving cache never
    needs that frame)."""
    if len(buf) < _PAGE_HDR.size:
        raise FrameError(f"transfer truncated ({len(buf)} bytes)")
    magic, ver, hlen = _PAGE_HDR.unpack_from(buf, 0)
    if magic != TRANSFER_MAGIC:
        raise FrameError(f"bad transfer magic {magic!r}")
    if ver != VERSION:
        raise FrameError(f"transfer version {ver} unsupported "
                         f"(this build speaks {VERSION})")
    off = _PAGE_HDR.size
    if off + hlen + _U64.size > len(buf):
        raise FrameError("transfer truncated inside its header")
    try:
        hdr = json.loads(buf[off:off + hlen])
    except ValueError as e:
        raise FrameError(f"transfer header is not JSON: {e}") from e
    off += hlen
    (tlen,) = _U64.unpack_from(buf, off)
    off += _U64.size
    if off + tlen + _SHA_LEN > len(buf):
        raise FrameError("transfer truncated inside its token block")
    head_end = off + tlen
    if hashlib.sha256(buf[:head_end]).digest() \
            != buf[head_end:head_end + _SHA_LEN]:
        raise FrameError("transfer header sha256 mismatch")
    tokens = np.frombuffer(buf, np.int32, count=tlen // 4, offset=off)
    if int(tokens.size) != int(hdr.get("n_tokens", -1)):
        raise FrameError("transfer token count disagrees with header")
    off = head_end + _SHA_LEN
    frames: List[bytes] = []
    for _ in range(int(hdr.get("n_frames", 0))):
        if off + _U64.size > len(buf):
            raise FrameError("transfer truncated at a frame boundary")
        (n,) = _U64.unpack_from(buf, off)
        off += _U64.size
        if off + n > len(buf):
            raise FrameError(
                f"transfer frame declares {n} bytes but only "
                f"{len(buf) - off} remain (interrupted mid-shipment)")
        frame = buf[off:off + n]
        check_frame(frame)
        frames.append(frame)
        off += n
    if off != len(buf):
        raise FrameError(f"{len(buf) - off} trailing bytes after the "
                         "last frame")
    return np.asarray(tokens, np.int32), frames, hdr


def frame_capacity(n_layers: int, page_size: int, heads: int,
                   head_dim: int, dtype) -> int:
    """Upper bound on a packed page frame for this pool geometry (the
    host store's fixed slot size). Exact modulo header digits — padded
    by a small slack so no legitimate frame is ever rejected."""
    shape = (n_layers, page_size, heads, head_dim)
    per = int(np.prod(shape)) * np.dtype(dtype).itemsize
    return _PAGE_HDR.size + 256 + 2 * (_U64.size + per) + _SHA_LEN


# ==========================================================================
# The host-RAM spill tier
# ==========================================================================
def _release_slab(shm: shared_memory.SharedMemory):
    """Module-level finalizer body (never a bound method: a method would
    keep the store alive and the finalizer would never fire)."""
    try:
        shm.close()
        shm.unlink()
    except Exception:   # graftlint: disable=bare-except-swallow -- best-
        # effort teardown at interpreter exit; the OS reclaims the
        # segment regardless and there is nobody left to tell
        pass


class HostPageStore:
    """Fixed-slot host-RAM page store over one SharedMemory slab.

    Demoted KV pages live here as packed frames keyed by their chained
    prefix-path digest; `get` promotes (MRU-touches) and `put` demotes,
    with LRU eviction once every slot is full — the same cache-not-
    working-memory contract as the HBM retained set, one tier down.
    Thread-safe; writes are copies into the pinned slab, so a frame
    handed back by `get` is immutable and durable the moment `put`
    returns (the spill-ordering guarantee kvcache eviction relies on).
    """

    def __init__(self, pages: int, frame_bytes: int, name: str = "lm",
                 time_fn: Callable[[], float] = None):
        if pages < 1 or frame_bytes < 1:
            raise ValueError(f"HostPageStore needs pages/frame_bytes "
                             f">= 1 (got {pages}/{frame_bytes})")
        self.pages = int(pages)
        #: slot layout: u64 payload length + the frame bytes
        self.slot_bytes = _U64.size + int(frame_bytes)
        self.frame_bytes = int(frame_bytes)
        self.name = name
        self._time = time_fn                # test seam (fake clocks)
        self._shm = shared_memory.SharedMemory(
            create=True, size=max(1, self.pages * self.slot_bytes))
        self._lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.kvfabric.HostPageStore._lock")
        #: digest -> slot index; insertion order == LRU order
        self._index: "OrderedDict[bytes, int]" = OrderedDict()
        self._free: List[int] = list(range(self.pages))
        self._bytes_used = 0
        self._last_put_at: Dict[bytes, float] = {}
        self._closed = False
        self._finalizer = weakref.finalize(self, _release_slab, self._shm)
        self._gauges()

    # ------------------------------------------------------------- metrics
    def _gauges(self):
        monitor.gauge(
            "serving_kv_spill_pages",
            "KV pages resident in the host-RAM spill tier",
            labels=("model",)).set(len(self._index), model=self.name)
        monitor.gauge(
            "serving_kv_spill_bytes",
            "Payload bytes resident in the host-RAM spill tier",
            labels=("model",)).set(self._bytes_used, model=self.name)

    # ------------------------------------------------------------- access
    def put(self, key: bytes, payload: bytes) -> bool:
        """Demote one packed frame under `key`. Durable (copied into the
        slab) before this returns True; False when the frame exceeds the
        slot size (metered, never an exception — a too-big frame just
        isn't spillable)."""
        if len(payload) > self.frame_bytes:
            monitor.counter(
                "serving_kv_spill_rejects_total",
                "Demotions rejected by the spill tier (frame larger "
                "than the configured slot)",
                labels=("model",)).inc(model=self.name)
            return False
        with self._lock:
            if self._closed:
                return False
            slot = self._index.get(key)
            if slot is None:
                if not self._free:
                    old_key, slot = self._index.popitem(last=False)
                    (old_len,) = _U64.unpack_from(
                        self._shm.buf, slot * self.slot_bytes)
                    self._bytes_used -= old_len
                    self._last_put_at.pop(old_key, None)
                    monitor.counter(
                        "serving_kv_spill_evictions_total",
                        "Spill-tier frames evicted LRU to make room for "
                        "a newer demotion",
                        labels=("model",)).inc(model=self.name)
                else:
                    slot = self._free.pop()
            else:
                (old_len,) = _U64.unpack_from(
                    self._shm.buf, slot * self.slot_bytes)
                self._bytes_used -= old_len
            base = slot * self.slot_bytes
            _U64.pack_into(self._shm.buf, base, len(payload))
            self._shm.buf[base + _U64.size:
                          base + _U64.size + len(payload)] = payload
            self._index[key] = slot
            self._index.move_to_end(key)
            self._bytes_used += len(payload)
            if self._time is not None:
                self._last_put_at[key] = self._time()
            monitor.counter(
                "serving_kv_spill_demotions_total",
                "KV pages demoted from the HBM pool into the host-RAM "
                "spill tier", labels=("model",)).inc(model=self.name)
            self._gauges()
            return True

    def get(self, key: bytes) -> Optional[bytes]:
        """Fetch a demoted frame (MRU touch); None when absent."""
        with self._lock:
            slot = self._index.get(key)
            if slot is None or self._closed:
                return None
            self._index.move_to_end(key)
            base = slot * self.slot_bytes
            (n,) = _U64.unpack_from(self._shm.buf, base)
            return bytes(self._shm.buf[base + _U64.size:
                                       base + _U64.size + n])

    def contains(self, key: bytes) -> bool:
        with self._lock:
            return key in self._index

    def drop(self, key: bytes):
        with self._lock:
            slot = self._index.pop(key, None)
            if slot is None:
                return
            (n,) = _U64.unpack_from(self._shm.buf,
                                    slot * self.slot_bytes)
            self._bytes_used -= n
            self._free.append(slot)
            self._last_put_at.pop(key, None)
            self._gauges()

    def keys(self, limit: int = 64) -> List[bytes]:
        """MRU-first resident keys (ownership advertisement input)."""
        with self._lock:
            out = list(reversed(self._index.keys()))
            return out[:max(0, int(limit))]

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def describe(self) -> dict:
        with self._lock:
            return {"pages": self.pages,
                    "resident": len(self._index),
                    "frame_bytes": self.frame_bytes,
                    "bytes_used": self._bytes_used}

    def close(self):
        with self._lock:
            self._closed = True
            self._index.clear()
            self._free = list(range(self.pages))
            self._bytes_used = 0
        self._finalizer()
