"""SLO-gated continuous rollout: checkpoint -> canary -> verdict ->
promote / auto-rollback.

The missing loop between training and serving: ResilientTrainer writes
checkpoints (and, through its eval gate, a ``blessed.json`` manifest
naming the one that passed eval); the RolloutController here tails that
manifest, deploys the new version as a **canary on one replica** behind
the ResilientRouter (the router keeps the canary's live-traffic share
bounded — see ``ResilientRouter.canary_fraction``), judges it over a
bounded observation window, then either **promotes fleet-wide** with a
staggered swap fan-out or **auto-rolls back**, firing a
``flight.trip("rollout_rejected")`` postmortem that names the regressing
metric and the slow trace ids.

Verdict inputs (all fetched per replica over the same transport the
router uses, so fakes in tests work unchanged):

- **accuracy probe set** — deterministic labelled examples POSTed to the
  canary right after deploy; a model that scrambles its outputs is
  rejected in seconds, before real traffic is burned;
- **availability** — the canary replica's own ``/v1/slo`` verdict
  (PR-16 burn-rate engine) vs the incumbents';
- **latency** — ``/v1/timeseries`` p99 of ``serving_request_seconds``
  over the observation window, canary vs incumbent.

While a rollout is in flight the controller *holds the fleet admin
surface*: manual ``swap``/``rollback`` fan-outs through the RouterServer
are refused with 409 (they would interleave with the canary/promote
sequence and fork the fleet's version history).

Deliberately tick()-driven with injectable time/sleep/transport seams —
the same testing contract as ReplicaSupervisor — so every decision path
is unit-testable without wall-clock waits.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import flight
from deeplearning4j_tpu.util.locks import DiagnosedLock

log = logging.getLogger("deeplearning4j_tpu")

#: controller states, also exported as the serving_rollout_state gauge
ROLLOUT_STATES = ("idle", "canary", "promoting", "rolling_back")


def read_blessed(directory: str) -> Optional[dict]:
    """The trainer-side blessing contract (CheckpointManager.bless):
    ``<dir>/blessed.json`` names the newest eval-approved checkpoint.
    Returns the manifest dict with ``path`` resolved to an existing
    file, or None (no blessing yet, or the blessed file vanished)."""
    manifest = os.path.join(directory, "blessed.json")
    try:
        with open(manifest) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    path = doc.get("path")
    if not path and doc.get("file"):
        path = os.path.join(directory, doc["file"])
    if not path or not os.path.exists(path):
        return None
    doc["path"] = path
    return doc


def _latest_manifest_entry(directory: str) -> Optional[dict]:
    """Raw-directory watch mode: newest manifest.json entry whose file
    exists. Read-only — no CheckpointManager instantiation (its init
    sweeps tmp files, which a watcher must not do to a live trainer's
    directory)."""
    try:
        with open(os.path.join(directory, "manifest.json")) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return None
    for entry in reversed(manifest.get("checkpoints", [])):
        path = os.path.join(directory, entry.get("file", ""))
        if entry.get("file") and os.path.exists(path):
            return {**entry, "path": path}
    return None


class RolloutController:
    """Watch a checkpoint directory, canary new versions, promote or
    roll back on SLO evidence.

    Parameters (the knob table lives in docs/SERVING.md):

    - ``supervisor`` / ``router`` — the fleet being rolled;
    - ``directory`` — checkpoint dir to tail; ``watch`` selects the
      eval-gated ``blessed.json`` manifest (default) or the raw
      ``latest`` manifest entry;
    - ``model`` — served model name the rollout swaps;
    - ``observe_s`` — canary observation window; the verdict is taken
      at its end (extended up to ``observe_extend`` × while the canary
      has seen fewer than ``min_canary_requests`` requests);
    - ``max_error_ratio_increase`` — canary error ratio may exceed the
      incumbents' by at most this much;
    - ``max_p99_ratio`` / ``p99_floor_ms`` — canary p99 may be at most
      ``max_p99_ratio`` × incumbent p99, ignored below the floor (a
      3 ms vs 1 ms "regression" is noise, not a verdict);
    - ``probe_set`` — optional ``[(example, expected_class), ...]``
      accuracy probes POSTed to the canary immediately after deploy;
      accuracy below ``probe_min_accuracy`` rejects on the spot;
    - ``promote_stagger_s`` — pause between per-replica swaps during
      fleet-wide promotion (one bad swap aborts before the fleet turns).

    time_fn / wall_fn / sleep_fn / transport are injectable seams;
    tests drive ``tick(now)`` directly and never touch the wall clock.
    """

    def __init__(self, supervisor, router, directory: str, model: str,
                 watch: str = "blessed",
                 poll_interval_s: float = 5.0,
                 observe_s: float = 30.0,
                 observe_extend: float = 3.0,
                 min_canary_requests: int = 20,
                 max_error_ratio_increase: float = 0.02,
                 max_p99_ratio: float = 1.5,
                 p99_floor_ms: float = 10.0,
                 probe_set: Optional[Sequence[Tuple[object, int]]] = None,
                 probe_min_accuracy: float = 0.8,
                 promote_stagger_s: float = 1.0,
                 admin_timeout_s: float = 30.0,
                 time_fn: Callable[[], float] = time.monotonic,
                 wall_fn: Callable[[], float] = time.time,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 transport=None):
        if watch not in ("blessed", "latest"):
            raise ValueError(f"watch must be 'blessed' or 'latest', "
                             f"got {watch!r}")
        if observe_s <= 0 or poll_interval_s <= 0:
            raise ValueError("observe_s and poll_interval_s must be > 0")
        if not 0.0 <= float(probe_min_accuracy) <= 1.0:
            raise ValueError("probe_min_accuracy must be in [0, 1]")
        if float(max_p99_ratio) < 1.0:
            raise ValueError("max_p99_ratio must be >= 1.0")
        self.supervisor = supervisor
        self.router = router
        self.directory = directory
        self.model = model
        self.watch = watch
        self.poll_interval_s = float(poll_interval_s)
        self.observe_s = float(observe_s)
        self.observe_extend = max(1.0, float(observe_extend))
        self.min_canary_requests = int(min_canary_requests)
        self.max_error_ratio_increase = float(max_error_ratio_increase)
        self.max_p99_ratio = float(max_p99_ratio)
        self.p99_floor_ms = float(p99_floor_ms)
        self.probe_set = list(probe_set) if probe_set else None
        self.probe_min_accuracy = float(probe_min_accuracy)
        self.promote_stagger_s = float(promote_stagger_s)
        self.admin_timeout_s = float(admin_timeout_s)
        self._time = time_fn
        self._wall = wall_fn
        self._sleep = sleep_fn
        self._transport = transport if transport is not None \
            else router._transport
        self._lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.rollout.RolloutController._lock")
        self.state = "idle"
        self.rollout_generation = 0
        self.canary: Optional[dict] = None
        self.last_verdict: Optional[dict] = None
        self.history: List[dict] = []
        #: identities (sha256 / file) already decided — a rejected
        #: checkpoint must not be re-canaried every poll
        self._decided = set()
        self.current_source = self._incumbent_source()
        self._next_poll = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ plumbing
    def _incumbent_source(self) -> Optional[str]:
        for r in self.supervisor.replicas:
            if r.spec is None:
                continue
            for name, src in list(r.spec.models) + list(r.spec.lms):
                if name == self.model:
                    return src
        return None

    def holds_admin(self) -> bool:
        """True while a rollout is using the fleet admin surface —
        RouterServer refuses manual swap/rollback with 409 meanwhile."""
        with self._lock:
            return self.state != "idle"

    def describe(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "model": self.model,
                    "watch": self.watch,
                    "directory": self.directory,
                    "rollout_generation": self.rollout_generation,
                    "current_source": self.current_source,
                    "canary": dict(self.canary) if self.canary else None,
                    "last_verdict": self.last_verdict,
                    "decisions": list(self.history[-16:])}

    def _set_state(self, state: str):
        # callers hold self._lock
        self.state = state
        monitor.gauge("serving_rollout_state",
                      "RolloutController state "
                      "(0 idle, 1 canary, 2 promoting, 3 rolling_back)"
                      ).set(float(ROLLOUT_STATES.index(state)))

    # -------------------------------------------------------------- thread
    def start(self, interval_s: Optional[float] = None):
        """Run the controller loop in a background thread (tick every
        ``interval_s``, default min(1, poll_interval_s))."""
        if self._thread is not None:
            return self
        tick_every = float(interval_s) if interval_s is not None \
            else min(1.0, self.poll_interval_s)
        self._stop.clear()

        def _loop():
            while not self._stop.wait(tick_every):
                try:
                    self.tick()
                except Exception:       # noqa: BLE001 — a crashed
                    # controller loop would silently freeze rollouts;
                    # log loud and keep ticking
                    log.exception("rollout: tick failed")

        self._thread = threading.Thread(target=_loop, daemon=True,
                                        name="rollout-controller")
        self._thread.start()
        log.info("rollout: watching %s (%s) for model %r",
                 self.directory, self.watch, self.model)
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    # ---------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None):
        """One deterministic control-loop step."""
        now = self._time() if now is None else now
        with self._lock:
            state = self.state
        if state == "idle":
            if now < self._next_poll:
                return
            self._next_poll = now + self.poll_interval_s
            cand = self._poll_source()
            if cand is not None:
                self._start_canary(cand, now)
        elif state == "canary":
            self._observe(now)
        # promoting / rolling_back are transient within a single tick

    # -------------------------------------------------------------- watch
    def _poll_source(self) -> Optional[dict]:
        """Next undecided candidate: {"path", "identity", ...} or None."""
        doc = read_blessed(self.directory) if self.watch == "blessed" \
            else _latest_manifest_entry(self.directory)
        if doc is None:
            return None
        identity = doc.get("sha256") or f"file:{os.path.basename(doc['path'])}"
        if identity in self._decided or doc["path"] == self.current_source:
            return None
        return {"path": doc["path"], "identity": identity,
                "metrics": doc.get("metrics"),
                "iteration": doc.get("iteration")}

    # ------------------------------------------------------------- canary
    def _admin(self, replica, verb: str, body: Optional[dict] = None):
        """POST /v1/models/{model}/{verb} to ONE replica (not fan_out —
        the whole point of a canary is one replica at a time). Returns
        (ok, response_doc)."""
        payload = json.dumps(body or {}).encode("utf-8")
        from deeplearning4j_tpu.serving.router import ReplicaTransportError
        try:
            code, _, raw = self._transport(
                replica, f"/v1/models/{self.model}/{verb}", payload,
                {"Content-Type": "application/json"}, self.admin_timeout_s)
        except ReplicaTransportError as e:
            return False, {"error": str(e)}
        try:
            doc = json.loads(raw) if raw else {}
        except ValueError:
            doc = {}
        return code == 200, doc

    def _pick_canary_replica(self):
        ready = [r for r in self.supervisor.healthy()
                 if r.role != "canary"
                 and getattr(r, "scaledown", None) is None]
        if len(ready) < 2:
            # never canary the only serving replica: a bad version
            # would take 100% of traffic, which is exactly what a
            # canary exists to prevent
            return None
        return min(ready, key=lambda r: r.inflight())

    def _start_canary(self, cand: dict, now: float):
        replica = self._pick_canary_replica()
        if replica is None:
            log.info("rollout: candidate %s waiting — need >= 2 ready "
                     "replicas to canary", cand["identity"])
            return
        ok, doc = self._admin(replica, "swap", {"source": cand["path"]})
        if not ok:
            self._decided.add(cand["identity"])
            monitor.counter("serving_rollout_deploy_failures_total",
                            "Canary deploy (swap) attempts that failed"
                            ).inc()
            decision = {"decision": "deploy_failed", "at": self._wall(),
                        "source": cand["path"],
                        "identity": cand["identity"],
                        "error": doc.get("error")}
            with self._lock:
                self.history.append(decision)
                self.last_verdict = decision
            log.error("rollout: canary deploy of %s on %s failed: %s",
                      cand["path"], replica.name, doc.get("error"))
            return
        with self._lock:
            self.rollout_generation += 1
            gen = self.rollout_generation
            self.canary = {
                "replica": replica.name,
                "replica_generation": replica.generation,
                "source": cand["path"],
                "identity": cand["identity"],
                "started_unix": self._wall(),
                "started": now,
                "observe_until": now + self.observe_s,
                "deadline": now + self.observe_s * self.observe_extend,
            }
            self._set_state("canary")
        replica.set_role("canary", gen)
        monitor.counter("serving_rollout_canaries_total",
                        "Canary deployments started").inc()
        log.warning("rollout: canary %s -> %s (gen %d, observing %.0fs)",
                    cand["path"], replica.name, gen, self.observe_s)
        # deterministic fast path: labelled probes catch a garbage model
        # in seconds, before live traffic has to burn for the verdict
        acc = self._run_probes(replica)
        if acc is not None and acc < self.probe_min_accuracy:
            self._reject(replica, "probe_accuracy", now,
                         details={"probe_accuracy": round(acc, 4),
                                  "probe_floor": self.probe_min_accuracy})

    def _run_probes(self, replica) -> Optional[float]:
        """Accuracy over the probe set against the canary replica, or
        None when no probe set is configured / nothing could be scored."""
        if not self.probe_set:
            return None
        from deeplearning4j_tpu.serving.router import ReplicaTransportError
        correct = scored = 0
        for example, expected in self.probe_set:
            body = json.dumps(
                {"inputs": [np.asarray(example).tolist()]}).encode("utf-8")
            try:
                code, _, raw = self._transport(
                    replica, f"/v1/models/{self.model}/predict", body,
                    {"Content-Type": "application/json"}, 10.0)
            except ReplicaTransportError:
                continue
            if code != 200:
                continue
            try:
                outputs = json.loads(raw).get("outputs")
                pred = int(np.argmax(np.asarray(outputs[0])))
            except (ValueError, TypeError, IndexError):
                continue
            scored += 1
            correct += int(pred == int(expected))
        return correct / scored if scored else None

    # ------------------------------------------------------------- verdict
    def _replica_stats(self, replica, window_s: float) -> dict:
        """Verdict inputs from one replica: /v1/slo (availability burn)
        + /v1/timeseries (windowed p99 + request count)."""
        from deeplearning4j_tpu.serving.router import ReplicaTransportError
        out = {"requests": None, "error_ratio": None, "p99_ms": None,
               "slo_state": None}
        try:
            code, _, raw = self._transport(replica, "/v1/slo", None, {}, 5.0)
            doc = json.loads(raw) if code == 200 else {}
        except (ReplicaTransportError, ValueError):
            doc = {}
        if doc.get("enabled"):
            out["slo_state"] = doc.get("state")
            for obj in doc.get("objectives", []):
                if obj.get("kind") == "availability":
                    # the engine exports the measured GOOD fraction
                    ratio = obj.get("ratio")
                    out["error_ratio"] = (None if ratio is None
                                          else round(1.0 - ratio, 6))
                    break
        path = (f"/v1/timeseries?series=serving_request_seconds"
                f"&window={window_s:g}&model={self.model}")
        try:
            code, _, raw = self._transport(replica, path, None, {}, 5.0)
            doc = json.loads(raw) if code == 200 else {}
        except (ReplicaTransportError, ValueError):
            doc = {}
        if doc.get("enabled") and "error" not in doc:
            out["requests"] = doc.get("count")
            p99 = doc.get("p99")
            out["p99_ms"] = None if p99 is None else p99 * 1e3
        return out

    def _slow_traces(self, replica, limit: int = 5) -> List[str]:
        """Slowest recent trace ids from the canary's flight recorder —
        the postmortem names the requests that burned the budget."""
        from deeplearning4j_tpu.serving.router import ReplicaTransportError
        try:
            code, _, raw = self._transport(replica, "/v1/debug/flight",
                                           None, {}, 5.0)
            doc = json.loads(raw) if code == 200 else {}
        except (ReplicaTransportError, ValueError):
            return []
        records = [r for r in doc.get("records", [])
                   if r.get("trace_id") and r.get("duration_ms") is not None]
        records.sort(key=lambda r: r["duration_ms"], reverse=True)
        return [r["trace_id"] for r in records[:limit]]

    @staticmethod
    def _aggregate(stats: List[dict]) -> dict:
        """Pool incumbent stats: request-weighted when counts exist."""
        out = {"requests": None, "error_ratio": None, "p99_ms": None,
               "firing": False}
        reqs = [s["requests"] for s in stats if s["requests"]]
        if reqs:
            out["requests"] = sum(reqs)
        ers = [s["error_ratio"] for s in stats
               if s["error_ratio"] is not None]
        if ers:
            out["error_ratio"] = sum(ers) / len(ers)
        p99s = [s["p99_ms"] for s in stats if s["p99_ms"] is not None]
        if p99s:
            out["p99_ms"] = sorted(p99s)[len(p99s) // 2]     # median
        out["firing"] = any(s["slo_state"] == "firing" for s in stats)
        return out

    def _observe(self, now: float):
        with self._lock:
            canary = dict(self.canary) if self.canary else None
        if canary is None:                    # raced with stop/reject
            return
        replica = next((r for r in self.supervisor.replicas
                        if r.name == canary["replica"]), None)
        if (replica is None
                or replica.generation != canary["replica_generation"]
                or replica.state in ("dead", "stopped")):
            # the canary crashed or was replaced mid-evaluation; its
            # relaunch loaded the INCUMBENT spec (canary deploys never
            # rewrite ReplicaSpec), so there is nothing to swap back —
            # just record the rejection
            self._reject(replica, "canary_crashed", now, swap_back=False)
            return
        if now < canary["observe_until"]:
            return
        window = max(now - canary["started"], 1.0)
        canary_stats = self._replica_stats(replica, window)
        if ((canary_stats["requests"] or 0) < self.min_canary_requests
                and now < canary["deadline"]):
            return                            # extend: not enough evidence
        incumbents = [r for r in self.supervisor.healthy()
                      if r.name != replica.name and r.role != "canary"]
        base = self._aggregate(
            [self._replica_stats(r, window) for r in incumbents])
        metric, details = self._verdict(canary_stats, base)
        if metric is None:
            self._promote(replica, canary, now,
                          {"canary": canary_stats, "incumbent": base})
        else:
            details.update({"canary": canary_stats, "incumbent": base})
            self._reject(replica, metric, now, details=details)

    def _verdict(self, c: dict, base: dict):
        """(regressing_metric, details) — metric None means promote."""
        if (c["requests"] or 0) < self.min_canary_requests:
            return "insufficient_traffic", {
                "canary_requests": c["requests"] or 0,
                "required": self.min_canary_requests}
        if c["slo_state"] == "firing" and not base["firing"]:
            return "slo_burn", {"canary_slo_state": c["slo_state"]}
        if c["error_ratio"] is not None:
            allowed = (base["error_ratio"] or 0.0) \
                + self.max_error_ratio_increase
            if c["error_ratio"] > allowed:
                return "error_ratio", {
                    "canary_error_ratio": round(c["error_ratio"], 6),
                    "allowed_error_ratio": round(allowed, 6)}
        if (c["p99_ms"] is not None and base["p99_ms"] is not None
                and c["p99_ms"] > self.p99_floor_ms
                and c["p99_ms"] > base["p99_ms"] * self.max_p99_ratio):
            return "latency_p99", {
                "canary_p99_ms": round(c["p99_ms"], 3),
                "incumbent_p99_ms": round(base["p99_ms"], 3),
                "max_p99_ratio": self.max_p99_ratio}
        return None, {}

    # ------------------------------------------------------------- promote
    def _promote(self, replica, canary: dict, now: float, stats: dict):
        with self._lock:
            self._set_state("promoting")
            gen = self.rollout_generation
        t0 = self._time()
        targets = [r for r in self.supervisor.healthy()
                   if r.name != replica.name and r.role != "canary"
                   and getattr(r, "scaledown", None) is None]
        swapped = []
        failed = None
        for i, target in enumerate(targets):
            if i and self.promote_stagger_s > 0:
                self._sleep(self.promote_stagger_s)
            ok, doc = self._admin(target, "swap",
                                  {"source": canary["source"]})
            if not ok:
                failed = (target, doc.get("error"))
                break
            swapped.append(target)
        if failed is not None:
            # one bad swap aborts the fan-out and reverts the replicas
            # already turned — a half-promoted fleet is the worst state
            target, err = failed
            log.error("rollout: promote swap failed on %s (%s); "
                      "reverting %d already-swapped replicas",
                      target.name, err, len(swapped))
            for r in swapped:
                self._admin(r, "rollback")
            self._reject(replica, "promote_swap_failed", now,
                         details={"failed_replica": target.name,
                                  "error": err, "reverted":
                                      [r.name for r in swapped]})
            return
        # restart durability (same contract as RouterServer swap): a
        # replica relaunched later must come up on the promoted source
        for r in self.supervisor.replicas:
            if r.spec is not None:
                r.spec.models = [(n, canary["source"] if n == self.model
                                  else s) for n, s in r.spec.models]
                r.spec.lms = [(n, canary["source"] if n == self.model
                               else s) for n, s in r.spec.lms]
        replica.set_role("stable", gen)
        for r in targets:
            r.set_role("stable", gen)
        promote_s = self._time() - t0
        decision = {"decision": "promoted", "at": self._wall(),
                    "source": canary["source"],
                    "identity": canary["identity"],
                    "replicas": [replica.name] + [r.name for r in targets],
                    "observe_s": round(now - canary["started"], 3),
                    "promote_s": round(promote_s, 3),
                    "stats": stats}
        with self._lock:
            self._decided.add(canary["identity"])
            self.current_source = canary["source"]
            self.canary = None
            self.last_verdict = decision
            self.history.append(decision)
            self._set_state("idle")
        monitor.counter("serving_rollout_promotions_total",
                        "Canaries promoted fleet-wide").inc()
        monitor.histogram("serving_rollout_promote_seconds",
                          "Fleet-wide staggered promotion fan-out "
                          "duration").observe(promote_s)
        log.warning("rollout: PROMOTED %s fleet-wide (%d replicas, "
                    "%.2fs fan-out)", canary["source"],
                    1 + len(targets), promote_s)

    # ------------------------------------------------------------ rollback
    def _reject(self, replica, metric: str, now: float,
                details: Optional[dict] = None, swap_back: bool = True):
        with self._lock:
            canary = dict(self.canary) if self.canary else {}
            self._set_state("rolling_back")
            gen = self.rollout_generation
        slow = self._slow_traces(replica) if replica is not None else []
        rolled_back = False
        if swap_back and replica is not None:
            ok, doc = self._admin(replica, "rollback")
            rolled_back = ok
            if not ok:
                # the replica still serves the rejected version — kill
                # it so the supervisor relaunches from the (incumbent)
                # ReplicaSpec; loud, but strictly better than leaving a
                # known-bad canary in the routing set
                log.error("rollout: rollback on %s failed (%s) — killing "
                          "so the supervisor relaunches on the incumbent",
                          replica.name, doc.get("error"))
                replica.kill()
        if replica is not None:
            replica.set_role("stable", gen)
        # decision-time clock, not tick-start `now`: a probe rejection
        # spends its detection latency INSIDE this tick (probe POSTs,
        # rollback), and that time belongs in the banked detect series
        decided = self._time()
        detect_s = decided - canary.get("started", now if now is not None
                                        else decided)
        decision = {"decision": "rejected", "at": self._wall(),
                    "metric": metric,
                    "source": canary.get("source"),
                    "identity": canary.get("identity"),
                    "replica": canary.get("replica"),
                    "detect_s": round(detect_s, 3),
                    "rolled_back": rolled_back,
                    "slow_traces": slow,
                    "details": details or {}}
        with self._lock:
            if canary.get("identity"):
                self._decided.add(canary["identity"])
            self.canary = None
            self.last_verdict = decision
            self.history.append(decision)
            self._set_state("idle")
        monitor.counter("serving_rollout_rollbacks_total",
                        "Canaries auto-rolled back by regressing metric",
                        labels=("metric",)).inc(metric=metric)
        monitor.histogram("serving_rollout_rollback_detect_seconds",
                          "Canary deploy -> rollback decision latency",
                          buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0,
                                   300.0, 600.0)).observe(detect_s)
        # the postmortem is the rollout's receipt: WHAT regressed, WHICH
        # requests burned, and the exact source that was rejected.
        # Tripped outside the lock (flight dumps to disk).
        flight.trip("rollout_rejected", model=self.model, metric=metric,
                    source=canary.get("source"),
                    canary_replica=canary.get("replica"),
                    detect_s=round(detect_s, 3),
                    slow_traces=slow or None,
                    **{k: v for k, v in (details or {}).items()
                       if isinstance(v, (int, float, str, bool))})
        log.error("rollout: REJECTED %s — regressing metric %r "
                  "(detected in %.1fs, rolled_back=%s)",
                  canary.get("source"), metric, detect_s, rolled_back)
