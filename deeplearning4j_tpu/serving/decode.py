"""Token-level continuous batching — the LLM decode runtime.

The shape-bucketed predict batcher (`serving/batcher.py`) assumes one
request = one forward. Autoregressive decode breaks that: a request is a
*sequence* of forwards with state (the KV cache), lengths vary per
request, and batching at request granularity (wait for the whole batch to
finish, then admit the next) idles slots behind the longest sequence.
This module implements the Orca/vLLM answer — iteration-level scheduling
over a paged KV cache — under this tree's serving invariants:

- **Fixed shapes, AOT-warmed.** Decode runs as ONE compiled program over
  ``slots`` fixed batch positions with an active mask; prefill compiles
  per bucket of a page-aligned ladder (`kvcache.default_prefill_buckets`).
  Every program is executed at load/swap time by `DecodeEngine.warm()`,
  and `serving_decode_compiles_total == serving_decode_warmup_runs_total`
  on /metrics is the ledger proof that no request ever waited on XLA —
  the exact contract `serving/batcher.py` established for predict.
- **Continuous batching.** `DecodeScheduler` admits queued requests into
  free slots *between token steps*: a late-joining request's first token
  (its prefill) lands while other sequences keep decoding — it never
  waits for the running batch to drain. Finished sequences free their
  slot and pages at the same granularity.
- **Prefill/decode phase split.** Prefill (compute-bound, whole prompt)
  and decode (memory-bound, one token) are separate compiled programs
  with separate metric families, so the roofline ledger sees each phase's
  real arithmetic intensity.
- **Sampling in-graph.** Greedy / temperature / top-k run inside the
  decode program (per-slot temperature and k operands), so the host sees
  only one int32 per slot per step.
- **Rolling hot swap.** A swap warms a complete replacement engine off
  the request path, then new admissions go to the new engine while
  in-flight sequences finish on the old one (their KV pages are only
  meaningful under the params that wrote them); the old engine retires
  when its last sequence ends. Zero 5xx, zero request-path compiles,
  bounded double-residency documented in docs/SERVING.md.

`ServedLM` packages an engine + scheduler + version history behind the
same servable surface `ServedModel` exposes (status / describe / swap /
rollback / shutdown), so the registry, HTTP server, fleet supervisor and
router treat LM servables like any other — per-variant routing of the
quantized servables (`quantize.py`) falls out of plain model naming.
"""
from __future__ import annotations

import dataclasses
import functools
import logging
import queue
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import flight
from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.layers.attention import (
    EmbeddingSequenceLayer, LayerNormLayer, MoEFeedForward,
    MultiHeadAttention, PositionalEmbeddingLayer, TransformerBlock,
    _merge_heads, _split_heads, dot_product_attention, rope,
)
from deeplearning4j_tpu.nn.layers.recurrent import RnnOutputLayer
from deeplearning4j_tpu.serving import kvcache, kvfabric
from deeplearning4j_tpu.serving.batcher import (
    DeadlineExceededError, ServerDrainingError, ServerOverloadedError,
)
from deeplearning4j_tpu.serving.quantize import (
    QUANT_MODES, is_spec_variant, parse_variant, qdot, qtake,
    quantize_params,
)
from deeplearning4j_tpu.util.params import own_tree
from deeplearning4j_tpu.util.locks import DiagnosedLock

log = logging.getLogger("deeplearning4j_tpu")

_LN = LayerNormLayer()          # the block-internal LN (default epsilon)

#: static ceiling for the in-graph top-k gate (per-request k is clipped)
TOP_K_MAX = 64

_TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)
_ITL_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 1)


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    """Decode-runtime sizing, fixed at servable load time (every knob
    here shapes a compiled program or the page pool)."""
    slots: int = 4                       # fixed decode batch positions
    page_size: int = 16                  # tokens per KV page
    max_context: Optional[int] = None    # default: the model's seq_length
    pool_pages: Optional[int] = None     # default: no oversubscription
    prefill_buckets: Optional[Sequence[int]] = None
    quantize: Optional[str] = None       # None | "int8" | "bf16"
    queue_limit: int = 64                # pending-join bound (full -> 429)
    max_new_tokens_cap: int = 1024       # server-side generation ceiling
    seed: int = 0                        # sampling PRNG stream
    #: share KV pages across requests with a common prompt prefix
    #: (radix-indexed, copy-on-write; released prefixes retained LRU)
    prefix_cache: bool = True
    #: per-scheduler-tick prefill-token budget: long uncached suffixes
    #: split into chunks of at most this many tokens, executed BETWEEN
    #: decode steps so one long prompt cannot stall every in-flight
    #: stream's inter-token latency. None = auto (4 pages); 0 = off
    #: (whole suffix in one program call, the pre-chunking behavior)
    prefill_chunk_tokens: Optional[int] = None
    #: speculative decoding (draft-verify): None = off. "int8"/"bf16"
    #: self-draft the target through a quantized variant of its own
    #: params; any other string is loaded as a servable source (it must
    #: serve the SAME vocab — mismatch is a loud ModelLoadError). The
    #: ``@spec[:draft=...,k=...]`` source suffix sets these per servable.
    spec_draft: Optional[str] = None
    spec_k: int = 4                      # draft tokens per verify round
    #: rolling acceptance-rate floor: over the last `spec_window` rounds
    #: of a stream, accepted/proposed below this turns speculation OFF
    #: for that stream (it plain-decodes to completion)
    spec_accept_floor: float = 0.4
    spec_window: int = 8                 # rounds in the acceptance window
    #: draft engine's page pool (its own second pool); None = derived
    #: like the target's (no oversubscription)
    spec_draft_pool_pages: Optional[int] = None
    #: host-RAM spill tier size in pages: zero-ref retained prefix pages
    #: demote here under HBM pool pressure and promote back on a hit, so
    #: the effective prefix cache is host-RAM sized. None/0 = off. Only
    #: the TARGET engine spills (the draft's cache is derivative)
    spill_pages: Optional[int] = None


def apply_variant(cfg: DecodeConfig, variant: Optional[str]) -> DecodeConfig:
    """Apply a parsed ``@<variant>`` source suffix to a DecodeConfig:
    ``int8``/``bf16`` select quantized weights, ``spec[:k=...,draft=...,
    floor=...,window=...,pool_pages=...]`` turns on speculative decoding
    (unset options keep the config's defaults)."""
    if variant is None:
        return cfg
    if variant in QUANT_MODES:
        return dataclasses.replace(cfg, quantize=variant)
    if is_spec_variant(variant):
        updates = {"spec_draft": cfg.spec_draft or "int8"}
        if variant.startswith("spec:"):
            for item in variant[len("spec:"):].split(","):
                if not item:
                    continue
                key, sep, val = item.partition("=")
                if not sep:
                    raise ValueError(
                        f"@spec option {item!r} is not key=value")
                if key == "draft":
                    updates["spec_draft"] = val
                elif key == "k":
                    updates["spec_k"] = int(val)
                elif key == "floor":
                    updates["spec_accept_floor"] = float(val)
                elif key == "window":
                    updates["spec_window"] = int(val)
                elif key == "pool_pages":
                    updates["spec_draft_pool_pages"] = int(val)
                else:
                    raise ValueError(
                        f"unknown @spec option {key!r}; known: draft, k, "
                        "floor, window, pool_pages")
        return dataclasses.replace(cfg, **updates)
    raise ValueError(f"unknown servable variant {variant!r}; known: "
                     f"{QUANT_MODES} or spec[:...]")


class GenerateRequest:
    """One in-flight generation: token events stream out through a queue
    (("token", id) / ("done", info) / ("error", exc))."""

    def __init__(self, prompt, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None,
                 deadline: Optional[float] = None):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.eos_id = None if eos_id is None else int(eos_id)
        #: absolute time.monotonic() budget for the WHOLE generation
        self.deadline = deadline
        self.events: "queue.Queue" = queue.Queue()
        self.enqueued = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.last_emit_at: Optional[float] = None
        self.n_emitted = 0
        self.version: Optional[int] = None
        self.finish_reason: Optional[str] = None
        #: prompt positions served from the shared prefix cache (set at
        #: admission) and prefill program executions it took to cover
        #: the uncached suffix (set when prefill completes)
        self.cached_tokens = 0
        self.prefill_chunks = 0
        #: speculative-decoding accounting: draft tokens proposed to /
        #: accepted by the verifier, and verify rounds run, for THIS
        #: stream (0/0/0 on plain decode) — ride the done event so one
        #: loadgen compares speculative and plain runs
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_rounds = 0
        self.cancelled = threading.Event()
        self.done = threading.Event()
        # the submitting thread's trace context (the HTTP handler binds
        # the request's ctx around generate()); the scheduler thread
        # records this stream's spans under it
        self.ctx = monitor.current_context()
        self.t0_pc = time.perf_counter()
        self._last_pc: Optional[float] = None

    # ------------------------------------------------------------- events
    def emit(self, token: int):
        self.n_emitted += 1
        now = time.monotonic()
        if self.first_token_at is None:
            self.first_token_at = now
        self.last_emit_at = now
        self._last_pc = time.perf_counter()
        self.events.put(("token", int(token)))

    def finish(self, reason: str):
        if self.done.is_set():
            return
        self.finish_reason = reason
        self.done.set()
        self.events.put(("done", {
            "finish_reason": reason,
            "tokens": self.n_emitted,
            "version": self.version,
            "cached_tokens": self.cached_tokens,
            "prefill_chunks": self.prefill_chunks,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "spec_rounds": self.spec_rounds,
        }))

    def fail(self, exc: Exception):
        if self.done.is_set():
            return
        self.finish_reason = "error"
        self.done.set()
        self.events.put(("error", exc))

    def cancel(self):
        """Client went away: the scheduler frees the slot at the next
        token boundary."""
        self.cancelled.set()


# ==========================================================================
# The engine: compiled prefill / decode / scoring programs + cache state
# ==========================================================================
class DecodeEngine:
    """Paged-KV decode runtime for one model version.

    Builds fixed-shape jitted programs from a MultiLayerNetwork whose
    stack is an LM the runtime understands (EmbeddingSequenceLayer,
    TransformerBlock / MoEFeedForward / LayerNormLayer /
    PositionalEmbeddingLayer bodies, RnnOutputLayer head — i.e. the
    models/transformer.py family). Params are laundered through
    `own_tree` at build (they may be numpy-backed from a checkpoint
    restore and the KV pools ARE donated alongside them every step) and
    optionally quantized (`quantize.py`).
    """

    def __init__(self, model, cfg: DecodeConfig, name: str = "lm"):
        from deeplearning4j_tpu.serving.registry import ModelLoadError
        self.cfg = cfg
        self.name = name
        conf = model.conf
        it = getattr(conf, "input_type", None)
        if it is None or not model.layers:
            raise ModelLoadError(
                f"decode[{name}]: model has no recurrent input_type; not "
                "an LM this runtime can drive")
        self.max_context = int(cfg.max_context or it.shape[0])
        if cfg.page_size < 1 or self.max_context % cfg.page_size:
            raise ModelLoadError(
                f"decode[{name}]: max_context {self.max_context} must be "
                f"a positive multiple of page_size {cfg.page_size}")
        # ---------------------------------------------- layer extraction
        self._plan: List[Tuple[str, object, str]] = []
        self._block_index: Dict[str, int] = {}
        self.vocab: Optional[int] = None
        self.n_heads = self.head_dim = None
        for i, layer in enumerate(model.layers):
            key = str(i)
            last = i == len(model.layers) - 1
            if isinstance(layer, EmbeddingSequenceLayer):
                self._plan.append(("embed", layer, key))
                self.vocab = int(layer.n_in)
            elif isinstance(layer, PositionalEmbeddingLayer):
                if layer.max_length < self.max_context:
                    raise ModelLoadError(
                        f"decode[{name}]: positional table "
                        f"({layer.max_length}) shorter than max_context "
                        f"({self.max_context})")
                self._plan.append(("posembed", layer, key))
            elif isinstance(layer, TransformerBlock):
                if not layer.causal:
                    raise ModelLoadError(
                        f"decode[{name}]: layer {i} is a non-causal "
                        "TransformerBlock; autoregressive decode needs "
                        "causal attention")
                h = layer.n_heads
                d = layer.n_out // layer.n_heads
                if self.n_heads not in (None, h) or \
                        self.head_dim not in (None, d):
                    raise ModelLoadError(
                        f"decode[{name}]: non-uniform head geometry "
                        "across blocks is not supported")
                self.n_heads, self.head_dim = h, d
                self._block_index[key] = len(self._block_index)
                self._plan.append(("block", layer, key))
            elif isinstance(layer, (LayerNormLayer, MoEFeedForward)):
                self._plan.append(("pertoken", layer, key))
            elif isinstance(layer, RnnOutputLayer) and last:
                self._plan.append(("head", layer, key))
                if self.vocab is None:
                    self.vocab = int(layer.n_out)
            elif isinstance(layer, MultiHeadAttention):
                raise ModelLoadError(
                    f"decode[{name}]: bare MultiHeadAttention at layer "
                    f"{i}; wrap it in a TransformerBlock for decode")
            else:
                raise ModelLoadError(
                    f"decode[{name}]: layer {i} "
                    f"({type(layer).__name__}) has no incremental decode "
                    "path")
        if not self._block_index or self.vocab is None:
            raise ModelLoadError(
                f"decode[{name}]: need at least one TransformerBlock and "
                "a vocabulary head")
        self.n_layers = len(self._block_index)
        # ------------------------------------------------------- buffers
        # laundered: restored checkpoints hand us numpy-backed leaves and
        # these params ride in every donating step call (PR-3 contract)
        params = own_tree(model.params)
        self._params = quantize_params(params, cfg.quantize)
        self._dtype = jnp.bfloat16 if cfg.quantize == "bf16" \
            else jnp.float32
        self.cache = kvcache.KVCacheState(
            cfg.slots, cfg.page_size, self.max_context,
            pool_pages=cfg.pool_pages, name=name,
            prefix_cache=cfg.prefix_cache)
        # per-tick prefill-token budget (page-aligned, rounded up): None
        # = auto (4 pages), <= 0 = chunking off
        if cfg.prefill_chunk_tokens is None:
            self.prefill_chunk_tokens = min(4 * cfg.page_size,
                                            self.max_context)
        elif cfg.prefill_chunk_tokens <= 0:
            self.prefill_chunk_tokens = 0
        else:
            self.prefill_chunk_tokens = min(
                self.max_context,
                ((int(cfg.prefill_chunk_tokens) + cfg.page_size - 1)
                 // cfg.page_size) * cfg.page_size)
        pool_shape = (self.n_layers, self.cache.pool_pages,
                      cfg.page_size, self.n_heads, self.head_dim)
        self._kpool = jnp.zeros(pool_shape, self._dtype)
        self._vpool = jnp.zeros(pool_shape, self._dtype)
        self.prefill_buckets = tuple(sorted(set(
            int(b) for b in (cfg.prefill_buckets
                             or kvcache.default_prefill_buckets(
                                 cfg.page_size, self.max_context)))))
        for b in self.prefill_buckets:
            if b < 1 or b % cfg.page_size or b > self.max_context:
                raise ModelLoadError(
                    f"decode[{name}]: prefill bucket {b} must be a "
                    f"page-aligned size <= max_context")
        # per-slot host state
        self._temps = np.zeros((cfg.slots,), np.float32)
        self._topks = np.zeros((cfg.slots,), np.int32)
        self._last_tokens = np.zeros((cfg.slots,), np.int32)
        self._counter = 0
        self._base_key = jax.random.PRNGKey(cfg.seed)
        self._compiled: set = set()
        self._closed = False
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=(1, 2))
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=(1, 2))
        self._chunk_jit = jax.jit(self._chunk_fn, donate_argnums=(1, 2))
        self._copy_jit = jax.jit(kvcache.copy_page, donate_argnums=(0, 1))
        self._logits_jit = jax.jit(self._logits_fn)
        # ---------------------------------------------- tiered KV fabric
        # page extract/land programs: extract reads one physical page
        # WITHOUT donating (the pools stay live), land scatters one page
        # back donating as every other pool writer does. Both take the
        # page id as a traced operand — ONE compile each serves every
        # page. They back the host-RAM spill tier and the disaggregated
        # prefill transfer path, so they exist (and warm) regardless of
        # whether spill is configured.
        self._extract_jit = jax.jit(self._extract_fn)
        self._land_jit = jax.jit(self._land_fn, donate_argnums=(0, 1))
        self.spill: Optional[kvfabric.HostPageStore] = None
        if cfg.spill_pages and int(cfg.spill_pages) > 0 \
                and cfg.prefix_cache:
            self.spill = kvfabric.HostPageStore(
                int(cfg.spill_pages),
                kvfabric.frame_capacity(self.n_layers, cfg.page_size,
                                        self.n_heads, self.head_dim,
                                        np.dtype(self._dtype)),
                name=name)
            self.cache.attach_spill(self.spill, self._demote_page,
                                    self._land_frame)
        # ---------------------------------------- speculative decoding
        # the draft is a full second engine (own params, own smaller
        # page pool, own compiled programs under "<name>.draft"); the
        # target keeps per-slot speculation state and the slot mapping
        self.draft: Optional["DecodeEngine"] = None
        self._verify_jit = None
        self._draft_slots: Dict[int, Optional[int]] = {}
        self._draft_origin: Dict[int, int] = {}
        self._spec_on = np.ones((cfg.slots,), bool)
        self._spec_hist = [deque(maxlen=max(1, int(cfg.spec_window)))
                           for _ in range(cfg.slots)]
        # host-side rejection/residual sampling stream (the draft's
        # in-graph Gumbel stream provides q; acceptance runs on the host)
        self._spec_rng = np.random.RandomState((cfg.seed ^ 0x5EC5) &
                                               0x7FFFFFFF)
        if cfg.spec_draft is not None:
            self._build_draft(model)

    def _build_draft(self, model):
        """Construct the speculative draft engine. ``spec_draft`` is a
        quantize mode (self-draft: the target's own params, int8/bf16) or
        any servable source with the SAME vocabulary — a mismatched draft
        would run every acceptance test over a different symbol set, so
        it is rejected loudly here, at deploy/swap time (the PR-11 vocab
        swap-rejection policy)."""
        from deeplearning4j_tpu.serving.registry import ModelLoadError
        cfg = self.cfg
        k = int(cfg.spec_k)
        if k < 1:
            raise ModelLoadError(
                f"decode[{self.name}]: spec_k must be >= 1 (got {k})")
        src = str(cfg.spec_draft)
        if src in QUANT_MODES:
            draft_model, dquant, dsrc = model, src, f"self@{src}"
        else:
            from deeplearning4j_tpu.serving.registry import load_servable
            base, dquant = parse_variant(src)
            draft_model, dsrc = load_servable(base), src
        dcfg = dataclasses.replace(
            cfg, quantize=dquant, max_context=self.max_context,
            pool_pages=cfg.spec_draft_pool_pages, spec_draft=None,
            spill_pages=None, seed=cfg.seed + 1)
        draft = DecodeEngine(draft_model, dcfg, name=f"{self.name}.draft")
        if draft.vocab != self.vocab:
            dvocab = draft.vocab
            draft.close()
            raise ModelLoadError(
                f"decode[{self.name}]: speculative draft {dsrc!r} has "
                f"vocab {dvocab}, target serves {self.vocab} — rejection "
                "sampling needs one symbol set (deploy a matching-vocab "
                "draft, or fix the tokenizer mismatch upstream)")
        self.draft = draft
        self._verify_jit = jax.jit(self._verify_fn, donate_argnums=(1, 2))
        draft._propose_jit = jax.jit(
            functools.partial(draft._spec_propose_fn, k),
            donate_argnums=(1, 2))

    @property
    def spec_enabled(self) -> bool:
        return self.draft is not None

    # --------------------------------------------------------- the forward
    def _forward_tokens(self, params, tokens, mask):
        """(B, T) ids -> ((B, T, V) pre-softmax logits, per-block roped
        (K, V) lists). The same primitive calls as the stock layers'
        apply() so full-sequence logits are bitwise those of
        net.output() at valid positions."""
        x = None
        kvs = []
        t = tokens.shape[1]
        pos = jnp.arange(t)[None]
        for kind, layer, key in self._plan:
            p = params[key]
            if kind == "embed":
                x = qtake(p["W"], tokens)
                if mask is not None:
                    x = x * mask[..., None].astype(x.dtype)
            elif kind == "posembed":
                x = x + p["P"][:t][None]
            elif kind == "pertoken":
                x, _ = layer.apply(p, {}, x, train=False, rng=None,
                                   mask=mask)
            elif kind == "block":
                x, k, v = self._block_full(layer, p, x, mask, pos)
                kvs.append((k, v))
            else:                                           # head
                z = qdot(x, p["W"])
                if "b" in p:
                    z = z + p["b"]
                x = z
        return x, kvs

    def _block_full(self, conf, p, x, mask, pos):
        """TransformerBlock full-sequence forward, returning the roped
        K / raw V the cache stores. Mirrors TransformerBlock.apply's
        dense path operation-for-operation."""
        h, _ = _LN.apply(p["ln1"], {}, x)
        a = p["attn"]
        q = _split_heads(qdot(h, a["Wq"]), conf.n_heads)
        k = _split_heads(qdot(h, a["Wk"]), conf.n_heads)
        v = _split_heads(qdot(h, a["Wv"]), conf.n_heads)
        if conf.use_rope:
            q = rope(q, pos)
            k = rope(k, pos)
        out = dot_product_attention(q, k, v, mask=mask, causal=conf.causal)
        y = qdot(_merge_heads(out), a["Wo"])
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        x = x + y
        h, _ = _LN.apply(p["ln2"], {}, x)
        h = get_activation(conf.activation)(qdot(h, p["W1"]) + p["b1"])
        h = qdot(h, p["W2"]) + p["b2"]
        y = x + h
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, k, v

    def _block_decode(self, conf, p, li, x, kpool, vpool, page_table,
                      seq_lens, active, pos):
        """One-token incremental block forward against the paged cache."""
        s = x.shape[0]
        h, _ = _LN.apply(p["ln1"], {}, x)
        a = p["attn"]
        q = _split_heads(qdot(h, a["Wq"]), conf.n_heads)
        k = _split_heads(qdot(h, a["Wk"]), conf.n_heads)
        v = _split_heads(qdot(h, a["Wv"]), conf.n_heads)
        if conf.use_rope:
            q = rope(q, pos)
            k = rope(k, pos)
        ps = self.cfg.page_size
        page_idx = seq_lens // ps
        phys = page_table[jnp.arange(s), page_idx]
        # inactive slots write their garbage row to the dump page
        phys = jnp.where(active, phys, kvcache.DUMP_PAGE)
        kpool, vpool = kvcache.append_token_kv(
            kpool, vpool, li, k[:, 0], v[:, 0], phys, seq_lens % ps)
        keys, vals = kvcache.gather_kv(kpool, vpool, li, page_table,
                                       self.max_context)
        # validity: cached positions 0..seq_len INCLUSIVE (the row this
        # step just appended is position seq_len)
        mask = (jnp.arange(self.max_context)[None, :]
                <= seq_lens[:, None]).astype(jnp.float32)
        out = dot_product_attention(q, keys, vals, mask=mask, causal=False)
        y = qdot(_merge_heads(out), a["Wo"])
        x = x + y
        h, _ = _LN.apply(p["ln2"], {}, x)
        h = get_activation(conf.activation)(qdot(h, p["W1"]) + p["b1"])
        h = qdot(h, p["W2"]) + p["b2"]
        return x + h, kpool, vpool

    def _block_chunk(self, conf, p, li, x, kpool, vpool, page_row, pos,
                     valid, start, mask):
        """Incremental block forward for a prefill CHUNK: Tb suffix
        tokens of ONE slot at absolute positions `pos` (= start +
        arange), attending the paged cache — cached prefix pages AND the
        chunk's own rows, written first. Chunks may start mid-page (the
        COW divergence recompute does), so rows scatter by absolute
        (page, offset), padding rows steered to the dump page."""
        h, _ = _LN.apply(p["ln1"], {}, x)
        a = p["attn"]
        q = _split_heads(qdot(h, a["Wq"]), conf.n_heads)
        k = _split_heads(qdot(h, a["Wk"]), conf.n_heads)
        v = _split_heads(qdot(h, a["Wv"]), conf.n_heads)
        if conf.use_rope:
            q = rope(q, pos[None])
            k = rope(k, pos[None])
        ps = self.cfg.page_size
        page_idx = jnp.clip(pos // ps, 0, page_row.shape[0] - 1)
        phys = jnp.where(valid, page_row[page_idx], kvcache.DUMP_PAGE)
        kpool, vpool = kvcache.write_chunk_kv(
            kpool, vpool, li, k[0], v[0], phys, pos % ps)
        keys, vals = kvcache.gather_kv(kpool, vpool, li, page_row[None],
                                       self.max_context)
        # validity is pure causality: every cached position < a query's
        # absolute position was written (by a donor prefill, an earlier
        # chunk, or this chunk's own scatter above); positions >= end sit
        # past every valid query and the causal mask excludes them
        out = dot_product_attention(q, keys, vals, mask=None, causal=True,
                                    q_offset=start)
        y = qdot(_merge_heads(out), a["Wo"])
        y = y * mask[..., None].astype(y.dtype)
        x = x + y
        h, _ = _LN.apply(p["ln2"], {}, x)
        h = get_activation(conf.activation)(qdot(h, p["W1"]) + p["b1"])
        h = qdot(h, p["W2"]) + p["b2"]
        y = x + h
        return y * mask[..., None].astype(y.dtype), kpool, vpool

    # ----------------------------------------------------------- sampling
    def _sample(self, logits, temps, topks, counter):
        """Greedy / temperature / top-k, per slot, in-graph (Gumbel-max:
        one argmax regardless of temperature)."""
        lg = logits.astype(jnp.float32)
        s, v = lg.shape
        greedy = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        kmax = min(TOP_K_MAX, v)
        top_vals, _ = jax.lax.top_k(lg, kmax)
        kth = top_vals[jnp.arange(s), jnp.clip(topks, 1, kmax) - 1]
        keep = (topks <= 0)[:, None] | (lg >= kth[:, None])
        filt = jnp.where(keep, lg, -jnp.inf)
        g = jax.random.gumbel(jax.random.fold_in(self._base_key, counter),
                              lg.shape, jnp.float32)
        safe_t = jnp.where(temps > 0, temps, 1.0)[:, None]
        sampled = jnp.argmax(filt / safe_t + g, axis=-1).astype(jnp.int32)
        return jnp.where(temps > 0, sampled, greedy)

    # ------------------------------------------------------- jitted bodies
    def _prefill_fn(self, params, kpool, vpool, tokens, length, page_row,
                    temp, topk, counter):
        """tokens (1, Tb); length (); page_row (pages_per_slot,). Returns
        (kpool, vpool, first sampled token (), last-position logits (V,))."""
        tb = tokens.shape[1]
        mask = (jnp.arange(tb)[None] < length).astype(jnp.float32)
        logits, kvs = self._forward_tokens(params, tokens, mask)
        for li, (k, v) in enumerate(kvs):
            kpool, vpool = kvcache.write_prompt_kv(
                kpool, vpool, li, k[0], v[0], page_row, self.cfg.page_size)
        last = jnp.take(logits[0], length - 1, axis=0)
        tok = self._sample(last[None], temp[None], topk[None], counter)[0]
        return kpool, vpool, tok, last

    def _chunk_fn(self, params, kpool, vpool, tokens, start, end, page_row,
                  temp, topk, counter):
        """Suffix-chunk prefill: tokens (1, Tb) are prompt positions
        [start, end) of one slot (bucket-padded past end - start), with
        everything before `start` already cached in the slot's pages
        (shared prefix and/or earlier chunks). start/end are traced
        scalars — ONE compiled program per bucket serves every cache-hit
        length and every chunk of the ladder. Returns (kpool, vpool,
        sampled token (), last-valid-position logits (V,)) — the sample
        is only meaningful on the final chunk (end == prompt length)."""
        tb = tokens.shape[1]
        pos = start + jnp.arange(tb)
        valid = pos < end
        mask = valid.astype(jnp.float32)[None]          # (1, Tb)
        x = None
        for kind, layer, key in self._plan:
            p = params[key]
            if kind == "embed":
                x = qtake(p["W"], tokens)
                x = x * mask[..., None].astype(x.dtype)
            elif kind == "posembed":
                idx = jnp.clip(pos, 0, layer.max_length - 1)
                x = x + jnp.take(p["P"], idx, axis=0)[None]
            elif kind == "pertoken":
                x, _ = layer.apply(p, {}, x, train=False, rng=None,
                                   mask=mask)
            elif kind == "block":
                x, kpool, vpool = self._block_chunk(
                    layer, p, self._block_index[key], x, kpool, vpool,
                    page_row, pos, valid, start, mask)
            else:                                       # head
                z = qdot(x, p["W"])
                if "b" in p:
                    z = z + p["b"]
                x = z
        last = jnp.take(x[0], jnp.clip(end - 1 - start, 0, tb - 1), axis=0)
        tok = self._sample(last[None], temp[None], topk[None], counter)[0]
        return kpool, vpool, tok, last

    def _step_body(self, params, kpool, vpool, page_table, seq_lens,
                   tokens, active):
        """The one-token decode forward shared — primitive call for
        primitive call — by the decode step AND each unrolled position of
        the speculative verify/propose programs: identical subgraphs are
        what makes verify logits bitwise-equal to sequential decode steps
        (the oracle greedy spec-parity rests on). Returns (kpool, vpool,
        logits (S, V))."""
        pos = seq_lens[:, None]
        x = None
        for kind, layer, key in self._plan:
            p = params[key]
            if kind == "embed":
                x = qtake(p["W"], tokens)[:, None, :]
            elif kind == "posembed":
                idx = jnp.clip(seq_lens, 0, layer.max_length - 1)
                x = x + jnp.take(p["P"], idx, axis=0)[:, None, :]
            elif kind == "pertoken":
                x, _ = layer.apply(p, {}, x, train=False, rng=None,
                                   mask=None)
            elif kind == "block":
                x, kpool, vpool = self._block_decode(
                    layer, p, self._block_index[key], x, kpool, vpool,
                    page_table, seq_lens, active, pos)
            else:
                z = qdot(x, p["W"])
                if "b" in p:
                    z = z + p["b"]
                x = z
        return kpool, vpool, x[:, 0, :]

    def _decode_fn(self, params, kpool, vpool, page_table, seq_lens,
                   tokens, active, temps, topks, counter):
        """One token for every slot (inactive slots compute masked
        garbage into the dump page). Returns (kpool, vpool, sampled (S,),
        logits (S, V))."""
        kpool, vpool, logits = self._step_body(
            params, kpool, vpool, page_table, seq_lens, tokens, active)
        toks = self._sample(logits, temps, topks, counter)
        return kpool, vpool, toks, logits

    def _verify_fn(self, params, kpool, vpool, page_table, seq_lens,
                   tokens, drafted, active):
        """The speculative verify: score k+1 positions per slot in ONE
        fixed-shape program — position 0 consumes the stream's last
        sampled token, positions 1..k consume the draft's proposals —
        writing each position's KV as it goes (rejected-tail rows land
        past the post-acceptance seq_len; the validity mask hides them
        until the next round overwrites). k+1 unrolled `_step_body`
        calls, NOT a chunked-attention reformulation: per-position logits
        must be bitwise those of k+1 sequential decode steps. Returns
        (kpool, vpool, logits (S, k+1, V))."""
        k = drafted.shape[1]
        outs = []
        tok = tokens
        for i in range(k + 1):
            kpool, vpool, logits = self._step_body(
                params, kpool, vpool, page_table, seq_lens + i, tok,
                active)
            outs.append(logits)
            if i < k:
                tok = drafted[:, i]
        return kpool, vpool, jnp.stack(outs, axis=1)

    def _spec_propose_fn(self, k, params, kpool, vpool, page_table,
                         seq_lens, tokens, active, temps, topks, counter):
        """The draft's fused propose program: k autoregressive tokens per
        slot in ONE dispatch (sampled in-graph, each fed to the next
        position), plus one extra body that consumes the k-th sample so
        the draft cache covers every token the target may accept — the
        next round then always resumes from exactly one new token
        regardless of where acceptance stopped. Returns (kpool, vpool,
        drafted (S, k), draft logits (S, k, V)); the logits give the
        host-side rejection sampler its q distribution."""
        drafted = []
        qlogits = []
        tok = tokens
        for i in range(k):
            kpool, vpool, logits = self._step_body(
                params, kpool, vpool, page_table, seq_lens + i, tok,
                active)
            tok = self._sample(logits, temps, topks, counter + i)
            drafted.append(tok)
            qlogits.append(logits)
        kpool, vpool, _ = self._step_body(
            params, kpool, vpool, page_table, seq_lens + k, tok, active)
        return (kpool, vpool, jnp.stack(drafted, axis=1),
                jnp.stack(qlogits, axis=1))

    def _logits_fn(self, params, tokens):
        """(B, T) -> (B, T, V) full-sequence pre-softmax logits (parity /
        quality scoring; never on the request path)."""
        return self._forward_tokens(params, tokens, None)[0]

    # ------------------------------------------------- tiered KV fabric
    def _extract_fn(self, kpool, vpool, page):
        """Read one physical page across every layer -> (K, V) each
        shaped (L, page_size, H, D). `page` is a traced scalar; the
        pools are NOT donated (the page must survive its own export)."""
        return (jax.lax.dynamic_index_in_dim(kpool, page, axis=1,
                                             keepdims=False),
                jax.lax.dynamic_index_in_dim(vpool, page, axis=1,
                                             keepdims=False))

    def _land_fn(self, kpool, vpool, page, kpage, vpage):
        """Write one (L, page_size, H, D) K/V pair into physical page
        `page` (traced scalar), donating the pools like every writer."""
        kpool = kpool.at[:, page].set(kpage)
        vpool = vpool.at[:, page].set(vpage)
        return kpool, vpool

    def _demote_page(self, page: int, digest: bytes) -> bytes:
        """Spill-extract callback: one HBM page -> a packed, sealed
        frame. SCHEDULER THREAD ONLY (the pools are donated buffers)."""
        self._meter_program("kv_extract", warmup=False)
        with monitor.span("serving/kv_extract", model=self.name):
            k, v = self._extract_jit(self._kpool, self._vpool,
                                     np.int32(page))
        return kvfabric.pack_page(np.asarray(k), np.asarray(v), digest)

    def _land_frame(self, page: int, payload: bytes, digest: bytes):
        """Spill-land callback: verify + write one frame into physical
        page `page`. Raises kvfabric.FrameError on corruption or a
        geometry that does not fit this pool — a clean rejection the
        caller degrades from. SCHEDULER THREAD ONLY."""
        k, v, _ = kvfabric.unpack_page(payload, expect_digest=digest)
        shape = (self.n_layers, self.cfg.page_size, self.n_heads,
                 self.head_dim)
        want = np.dtype(self._dtype)
        if k.shape != shape or k.dtype != want or v.dtype != want:
            raise kvfabric.FrameError(
                f"frame geometry {k.shape}/{k.dtype} does not fit pool "
                f"{shape}/{want} (mismatched model or quantize mode)")
        self._meter_program("kv_land", warmup=False)
        with monitor.span("serving/kv_land", model=self.name):
            self._kpool, self._vpool = self._land_jit(
                self._kpool, self._vpool, np.int32(page),
                jnp.asarray(k), jnp.asarray(v))

    def export_pages(self, tokens) -> List[bytes]:
        """Serialize the cached pages covering `tokens`' full blocks
        (which must all be radix-indexed — the caller prefills first)
        into sealed frames for a disaggregated transfer. SCHEDULER
        THREAD ONLY (runs as a fabric job)."""
        _, keys = self.cache._blocks(tokens)
        with self.cache._lock:
            node, pages = self.cache._walk_locked(keys)
            if len(pages) < len(keys):
                raise RuntimeError(
                    f"decode[{self.name}]: prefix fell out of the cache "
                    f"mid-export ({len(pages)}/{len(keys)} blocks "
                    "indexed); retry after re-prefilling")
            digests = kvfabric.chain_digests(keys)
        frames = []
        for page, dig in zip(pages, digests):
            self._meter_program("kv_extract", warmup=False)
            with monitor.span("serving/kv_extract", model=self.name):
                k, v = self._extract_jit(self._kpool, self._vpool,
                                         np.int32(page))
            frames.append(kvfabric.pack_page(np.asarray(k),
                                             np.asarray(v), dig))
        return frames

    def import_pages(self, tokens, frames: List[bytes]) -> int:
        """Adopt a shipment of sealed frames as this cache's retained
        prefix pages (the disaggregated-prefill landing). Frame i lands
        for block i via the verified land program; corruption raises
        kvfabric.FrameError cleanly. SCHEDULER THREAD ONLY."""
        _, keys = self.cache._blocks(tokens)
        if len(frames) != len(keys):
            raise kvfabric.FrameError(
                f"shipment has {len(frames)} frames for {len(keys)} "
                "full token blocks")
        digests = kvfabric.chain_digests(keys)

        def land(i: int, page: int):
            self._land_frame(page, frames[i], digests[i])

        return self.cache.adopt_pages(tokens, land)

    # ----------------------------------------------------- compile ledger
    def _meter_program(self, program: str, warmup: bool):
        if program in self._compiled:
            return
        self._compiled.add(program)
        monitor.counter(
            "serving_decode_compiles_total",
            "First executions of a decode-runtime program per engine "
            "generation (each implies one XLA compile)",
            labels=("model", "program")).inc(model=self.name,
                                             program=program)
        if not warmup:
            log.warning(
                "decode[%s]: program %s first executed on the REQUEST "
                "path (compile latency hit a live stream) — warm() was "
                "skipped or the ladder changed", self.name, program)

    def warm(self):
        """AOT-execute every prefill bucket and the decode step so no
        live stream ever waits on XLA. Installed counters satisfy
        compiles == warmups on /metrics (the generation ledger)."""
        t0 = time.perf_counter()
        dump_row = np.full((self.cache.pages_per_slot,),
                           kvcache.DUMP_PAGE, np.int32)
        # one handle, one help string: the registry is first-caller-wins
        # on help text, so retyping it per warmup site invites the
        # /metrics-vs-docs drift this family's ledger exists to prevent
        warmups = monitor.counter(
            "serving_decode_warmup_runs_total",
            "AOT decode-runtime warmup executions (one per program per "
            "engine generation)", labels=("model",))
        for tb in self.prefill_buckets:
            self._meter_program(f"prefill_{tb}", warmup=True)
            with monitor.span("serving/prefill", model=self.name,
                              bucket=tb, warmup=1):
                self._kpool, self._vpool, _, _ = self._prefill_jit(
                    self._params, self._kpool, self._vpool,
                    np.zeros((1, tb), np.int32), np.int32(1), dump_row,
                    np.float32(0), np.int32(0), np.uint32(0))
            warmups.inc(model=self.name)
        # the chunk ladder: suffix prefill after a cache hit and budgeted
        # chunks of a long prompt run through these — same buckets, one
        # extra program each (start/end are operands, not shapes)
        for tb in self.prefill_buckets:
            self._meter_program(f"chunk_{tb}", warmup=True)
            with monitor.span("serving/prefill_chunk", model=self.name,
                              bucket=tb, warmup=1):
                self._kpool, self._vpool, _, _ = self._chunk_jit(
                    self._params, self._kpool, self._vpool,
                    np.zeros((1, tb), np.int32), np.int32(0), np.int32(1),
                    dump_row, np.float32(0), np.int32(0), np.uint32(0))
            warmups.inc(model=self.name)
        # the COW page copy (dump -> dump during warmup: page 0 is
        # garbage by contract, so the no-op-shaped copy is safe)
        self._meter_program("cow_copy", warmup=True)
        with monitor.span("serving/kv_cow", model=self.name, warmup=1):
            self._kpool, self._vpool = self._copy_jit(
                self._kpool, self._vpool, np.int32(kvcache.DUMP_PAGE),
                np.int32(kvcache.DUMP_PAGE))
        warmups.inc(model=self.name)
        # the KV-fabric page programs (spill demote/promote + the
        # disaggregated transfer path): extract reads the dump page,
        # land writes the extracted garbage straight back to it
        self._meter_program("kv_extract", warmup=True)
        with monitor.span("serving/kv_extract", model=self.name, warmup=1):
            kx, vx = self._extract_jit(self._kpool, self._vpool,
                                       np.int32(kvcache.DUMP_PAGE))
        warmups.inc(model=self.name)
        self._meter_program("kv_land", warmup=True)
        with monitor.span("serving/kv_land", model=self.name, warmup=1):
            self._kpool, self._vpool = self._land_jit(
                self._kpool, self._vpool, np.int32(kvcache.DUMP_PAGE),
                kx, vx)
        warmups.inc(model=self.name)
        self._meter_program("decode", warmup=True)
        with monitor.span("serving/decode_step", model=self.name, warmup=1):
            s = self.cfg.slots
            self._kpool, self._vpool, _, _ = self._decode_jit(
                self._params, self._kpool, self._vpool,
                np.asarray(self.cache.page_table),
                np.zeros((s,), np.int32), np.zeros((s,), np.int32),
                np.zeros((s,), bool), np.zeros((s,), np.float32),
                np.zeros((s,), np.int32), np.uint32(0))
        warmups.inc(model=self.name)
        if self.draft is not None:
            # the draft engine warms its own ledger (programs metered
            # under "<name>.draft"), then the two speculative programs:
            # the fused k-token propose (draft's) and the k+1-position
            # verify (target's) — zero request-path compiles with
            # speculation live is part of the compiles==warmups contract
            d = self.draft
            d.warm()
            k = int(self.cfg.spec_k)
            ds = d.cfg.slots
            d._meter_program(f"draft_{k}", warmup=True)
            with monitor.span("serving/spec_draft", model=self.name,
                              warmup=1):
                d._kpool, d._vpool, _, _ = d._propose_jit(
                    d._params, d._kpool, d._vpool,
                    np.asarray(d.cache.page_table),
                    np.zeros((ds,), np.int32), np.zeros((ds,), np.int32),
                    np.zeros((ds,), bool), np.zeros((ds,), np.float32),
                    np.zeros((ds,), np.int32), np.uint32(0))
            warmups.inc(model=d.name)
            self._meter_program(f"verify_{k + 1}", warmup=True)
            with monitor.span("serving/spec_verify", model=self.name,
                              warmup=1):
                self._kpool, self._vpool, _ = self._verify_jit(
                    self._params, self._kpool, self._vpool,
                    np.asarray(self.cache.page_table),
                    np.zeros((s,), np.int32), np.zeros((s,), np.int32),
                    np.zeros((s, k), np.int32), np.zeros((s,), bool))
            warmups.inc(model=self.name)
        monitor.histogram(
            "serving_decode_warmup_seconds",
            "Full decode-runtime warmup duration (buckets + step)",
            labels=("model",),
            buckets=(0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120)).observe(
            time.perf_counter() - t0, model=self.name)

    # ------------------------------------------------------------ host API
    def bucket_for(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    def admit_prompt(self, prompt: np.ndarray
                     ) -> Optional[kvcache.AdmitInfo]:
        """Token-aware admission: claim a slot, map the longest cached
        prefix read-shared, and resolve any copy-on-write divergence
        on-device (the forced last-token recompute of a fully-cached
        page-aligned prompt writes into a private page copy, never into
        the shared one). None when slots/pages are exhausted."""
        info = self.cache.admit_prompt(prompt)
        if info is None:
            return None
        if info.cow_src is not None:
            try:
                self._meter_program("cow_copy", warmup=False)
                with monitor.span("serving/kv_cow", model=self.name):
                    self._kpool, self._vpool = self._copy_jit(
                        self._kpool, self._vpool, np.int32(info.cow_src),
                        np.int32(info.cow_dst))
            except Exception:
                # a failed copy must not leak the slot or the pinned
                # source page — undo the admission before surfacing
                self.cache.release(info.slot)
                self.cache.unref_page(info.cow_src)
                raise
            self.cache.unref_page(info.cow_src)
        if self.draft is not None:
            self._admit_draft(info.slot, prompt)
        return info

    def _admit_draft(self, slot: int, prompt: np.ndarray):
        """Mirror a successful target admission into the draft's (own,
        typically smaller) pool. A dry draft pool never blocks the
        stream — it just decodes plain (speculation off, metered as a
        fallback)."""
        self._spec_on[slot] = True
        self._spec_hist[slot].clear()
        dinfo = None
        try:
            dinfo = self.draft.admit_prompt(
                np.asarray(prompt, np.int32))
        except Exception:   # noqa: BLE001 — draft trouble must never
            # take down an admission the target already accepted
            log.exception("decode[%s]: draft admission failed; stream "
                          "decodes plain", self.name)
        if dinfo is None:
            self.spec_disable(slot, "draft_admit")
        else:
            self._draft_slots[slot] = int(dinfo.slot)
            self._draft_origin[slot] = int(dinfo.cached_len)

    def spec_disable(self, slot: int, reason: str):
        """Turn speculation off for ONE stream (it plain-decodes to
        completion) and free its draft pages for the streams still
        speculating. Metered per reason: draft_admit / draft_prefill /
        draft_pages / acceptance_floor."""
        self._spec_on[slot] = False
        ds = self._draft_slots.get(slot)
        self._draft_slots[slot] = None
        if ds is not None and self.draft is not None:
            self.draft.cache.release(ds)
        monitor.counter(
            "serving_decode_spec_fallbacks_total",
            "Streams whose speculation turned off (draft pool dry, "
            "draft prefill failure, or rolling acceptance under the "
            "floor)", labels=("model", "reason")).inc(
            model=self.name, reason=reason)

    def release_slot(self, slot: int):
        """Release a finished stream's target slot AND its draft mirror
        (scheduler call sites use this, never cache.release directly)."""
        self.cache.release(slot)
        if self.draft is not None:
            ds = self._draft_slots.pop(slot, None)
            self._draft_origin.pop(slot, None)
            if ds is not None:
                self.draft.cache.release(ds)
            self._spec_on[slot] = True
            self._spec_hist[slot].clear()

    def draft_prefill_origin(self, slot: int) -> Optional[int]:
        """Where the draft's prefill starts for this stream (its own
        cached-prefix length), or None when the stream speculates not."""
        if self.draft is None or self._draft_slots.get(slot) is None:
            return None
        return self._draft_origin.get(slot, 0)

    def draft_prefill(self, slot: int, prompt: np.ndarray, start: int,
                      n: int, temperature: float, top_k: int):
        """Advance the draft's prefill for `slot` by prompt positions
        [start, start+n) — same dense-vs-chunk split as the target's
        path; the sampled token is discarded (the stream's first token
        comes from the TARGET's prefill)."""
        ds = self._draft_slots[slot]
        if start == 0 and n == len(prompt):
            self.draft.prefill(ds, prompt, temperature, top_k)
        else:
            self.draft.prefill_chunk(ds, prompt, start, n, temperature,
                                     top_k)

    def draft_prefill_done(self, slot: int, prompt: np.ndarray):
        """Draft prefill complete: index the draft's prompt pages so the
        NEXT admission of this prefix is a draft-side cache hit too."""
        ds = self._draft_slots.get(slot)
        if ds is not None:
            self.draft.cache.register_prefix(ds, prompt)

    def prefill_chunk(self, slot: int, prompt: np.ndarray, start: int,
                      n: int, temperature: float, top_k: int) -> int:
        """Run prompt positions [start, start+n) through the paged-cache
        chunk program into `slot`'s pages (everything before `start` is
        already cached there). Returns the sampled token — meaningful
        only when this was the final chunk (start+n == len(prompt))."""
        tb = self.bucket_for(n)
        toks = np.zeros((1, tb), np.int32)
        toks[0, :n] = prompt[start:start + n]
        self._temps[slot] = temperature
        self._topks[slot] = top_k
        self._counter += 1
        self._meter_program(f"chunk_{tb}", warmup=False)
        with monitor.span("serving/prefill_chunk", model=self.name,
                          bucket=tb, tokens=n):
            self._kpool, self._vpool, tok, _ = self._chunk_jit(
                self._params, self._kpool, self._vpool, toks,
                np.int32(start), np.int32(start + n),
                self.cache.page_table[slot].copy(),
                np.float32(temperature), np.int32(top_k),
                np.uint32(self._counter & 0xFFFFFFFF))
        monitor.counter("serving_decode_prefills_total",
                        "Prefill program executions by bucket size "
                        "(chunk_* buckets are suffix/chunked prefills)",
                        labels=("model", "bucket")).inc(
            model=self.name, bucket=f"chunk_{tb}")
        tok = int(tok)
        self._last_tokens[slot] = tok
        return tok

    def prefill(self, slot: int, prompt: np.ndarray, temperature: float,
                top_k: int) -> Tuple[int, np.ndarray]:
        """Run the prompt through a bucket-padded prefill into `slot`'s
        pages; returns (first sampled token, last-position logits)."""
        n = int(len(prompt))
        tb = self.bucket_for(n)
        toks = np.zeros((1, tb), np.int32)
        toks[0, :n] = prompt
        self._temps[slot] = temperature
        self._topks[slot] = top_k
        self._counter += 1
        self._meter_program(f"prefill_{tb}", warmup=False)
        with monitor.span("serving/prefill", model=self.name, bucket=tb):
            self._kpool, self._vpool, tok, logits = self._prefill_jit(
                self._params, self._kpool, self._vpool, toks,
                np.int32(n), self.cache.page_table[slot].copy(),
                np.float32(temperature), np.int32(top_k),
                np.uint32(self._counter & 0xFFFFFFFF))
        monitor.counter("serving_decode_prefills_total",
                        "Prefill program executions by bucket size "
                        "(chunk_* buckets are suffix/chunked prefills)",
                        labels=("model", "bucket")).inc(
            model=self.name, bucket=str(tb))
        tok = int(tok)
        self._last_tokens[slot] = tok
        return tok, np.asarray(logits, np.float32)

    def step(self, exclude=()) -> Tuple[np.ndarray, np.ndarray,
                                        np.ndarray]:
        """One decode iteration over every runnable slot. Returns
        (sampled tokens (S,), runnable mask (S,), logits (S, V)); slots
        not in the mask were inactive, excluded (mid-prefill), page-
        stalled, or at the context cap and produced garbage."""
        act = np.zeros((self.cfg.slots,), bool)
        excl = frozenset(int(s) for s in exclude)
        n_runnable = 0
        for s in self.cache.active_slots():
            if s in excl:
                continue                # prefill still in flight
            if self.cache.ensure_page(s):
                act[s] = True
                n_runnable += 1
        self._counter += 1
        self._meter_program("decode", warmup=False)
        with monitor.span("serving/decode_step", model=self.name,
                          active=n_runnable):
            self._kpool, self._vpool, toks, logits = self._decode_jit(
                self._params, self._kpool, self._vpool,
                np.asarray(self.cache.page_table),
                np.asarray(self.cache.seq_lens), self._last_tokens.copy(),
                act, self._temps.copy(), self._topks.copy(),
                np.uint32(self._counter & 0xFFFFFFFF))
        toks_np = np.asarray(toks)
        for s in np.nonzero(act)[0]:
            self.cache.advance(int(s))
            self._last_tokens[s] = toks_np[s]
        monitor.counter("serving_decode_steps_total",
                        "Compiled decode iterations executed",
                        labels=("model",)).inc(model=self.name)
        return toks_np, act, np.asarray(logits, np.float32)

    # ------------------------------------------------- speculative decoding
    def _spec_dist(self, logits, temp: float, topk: int) -> np.ndarray:
        """The sampling distribution `_sample` draws from, recomputed on
        the host (float64): top-k filtering with the SAME clip against
        TOP_K_MAX, then temperature softmax. Rejection sampling is only
        exact when this q/p matches the in-graph Gumbel-max sampler's
        distribution term for term."""
        lg = np.asarray(logits, np.float64)
        v = lg.shape[-1]
        if topk > 0:
            kk = min(max(int(topk), 1), min(TOP_K_MAX, v))
            kth = np.sort(lg)[-kk]
            lg = np.where(lg >= kth, lg, -np.inf)
        z = lg / max(float(temp), 1e-30)
        z = z - z.max()
        p = np.exp(z)
        return p / p.sum()

    def _spec_accept(self, drafted, vlog, qlog, temp: float, topk: int
                     ) -> Tuple[int, int]:
        """Accept/reject one stream's k draft proposals against the
        target's k+1 verify logits. Returns (accepted count a, the one
        extra token): greedy is exact prefix-match on argmax with the
        target's own argmax at the first mismatch (bitwise the
        non-speculative stream); temperature is true rejection sampling
        — accept d_i with prob min(1, p(d_i)/q(d_i)), resample the first
        rejection from the residual max(p - q, 0), and on full
        acceptance sample the bonus token from the target's (k+1)-th
        distribution."""
        k = len(drafted)
        if temp <= 0:
            a = 0
            for i in range(k):
                if int(np.argmax(vlog[i])) == int(drafted[i]):
                    a += 1
                else:
                    break
            return a, int(np.argmax(vlog[a]))
        for i in range(k):
            d = int(drafted[i])
            p = self._spec_dist(vlog[i], temp, topk)
            q = self._spec_dist(qlog[i], temp, topk)
            if q[d] > 0.0 and self._spec_rng.random_sample() \
                    < min(1.0, float(p[d]) / float(q[d])):
                continue
            res = np.maximum(p - q, 0.0)
            tot = float(res.sum())
            if tot <= 0.0:
                res, tot = p, float(p.sum())    # p == q: any sample of
                # p is already correctly distributed
            return i, int(self._spec_rng.choice(len(res), p=res / tot))
        p = self._spec_dist(vlog[k], temp, topk)
        return k, int(self._spec_rng.choice(len(p), p=p / p.sum()))

    def spec_step(self, exclude=()) -> Dict[int, dict]:
        """One speculative round over every eligible stream: the draft
        proposes k tokens for all of them in ONE dispatch, the target
        scores all k+1 positions in ONE dispatch, and the host accepts
        per slot. Both caches advance by accepted+1 (the draft's propose
        program already consumed its own k-th sample, so whatever prefix
        survives, the next round resumes from exactly one new token).

        Returns {slot: {"tokens": [...], "proposed": k, "accepted": a}}
        for every slot handled this round — the scheduler emits those
        bursts and excludes the slots from the plain step. Slots under
        page/context pressure are simply left for the plain path this
        round; a dry DRAFT pool or a collapsed acceptance window turns
        speculation off for that stream (`spec_disable`)."""
        if self.draft is None:
            return {}
        k = int(self.cfg.spec_k)
        excl = frozenset(int(s) for s in exclude)
        pairs = []
        for s in self.cache.active_slots():
            if s in excl or not self._spec_on[s]:
                continue
            ds = self._draft_slots.get(s)
            if ds is None:
                continue
            if not self.cache.ensure_capacity(s, k + 1):
                # target page stall or context cap: the plain step's
                # per-token path copes (and finishes length_cap streams)
                continue
            if not self.draft.cache.ensure_capacity(ds, k + 1):
                self.spec_disable(s, "draft_pages")
                continue
            pairs.append((s, ds))
        if not pairs:
            return {}
        d = self.draft
        dact = np.zeros((d.cfg.slots,), bool)
        dtok = d._last_tokens.copy()
        for s, ds in pairs:
            dact[ds] = True
            # the draft extends the TARGET's stream: it consumes the
            # target's last sampled token, not its own prefill sample
            dtok[ds] = self._last_tokens[s]
        d._counter += k
        d._meter_program(f"draft_{k}", warmup=False)
        with monitor.span("serving/spec_draft", model=self.name,
                          active=len(pairs)):
            d._kpool, d._vpool, drafted, qlog = d._propose_jit(
                d._params, d._kpool, d._vpool,
                np.asarray(d.cache.page_table),
                np.asarray(d.cache.seq_lens), dtok, dact,
                d._temps.copy(), d._topks.copy(),
                np.uint32((d._counter - k + 1) & 0xFFFFFFFF))
        drafted = np.asarray(drafted)
        qlog = np.asarray(qlog, np.float32)
        tact = np.zeros((self.cfg.slots,), bool)
        vdraft = np.zeros((self.cfg.slots, k), np.int32)
        for s, ds in pairs:
            tact[s] = True
            vdraft[s] = drafted[ds]
        self._meter_program(f"verify_{k + 1}", warmup=False)
        with monitor.span("serving/spec_verify", model=self.name,
                          active=len(pairs)):
            self._kpool, self._vpool, vlog = self._verify_jit(
                self._params, self._kpool, self._vpool,
                np.asarray(self.cache.page_table),
                np.asarray(self.cache.seq_lens),
                self._last_tokens.copy(), vdraft, tact)
        vlog = np.asarray(vlog, np.float32)
        out: Dict[int, dict] = {}
        n_prop = n_acc = 0
        ratio = monitor.histogram(
            "serving_decode_spec_acceptance_ratio",
            "Per-stream-per-round fraction of draft proposals the "
            "verifier accepted (accepted / k)", labels=("model",),
            buckets=(0.0, 0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875,
                     1.0))
        floor = float(self.cfg.spec_accept_floor)
        for s, ds in pairs:
            a, extra = self._spec_accept(
                vdraft[s], vlog[s], qlog[ds], float(self._temps[s]),
                int(self._topks[s]))
            for _ in range(a + 1):
                self.cache.advance(s)
                d.cache.advance(ds)
            self._last_tokens[s] = extra
            d._last_tokens[ds] = extra
            n_prop += k
            n_acc += a
            out[s] = {"tokens": [int(t) for t in vdraft[s][:a]]
                      + [int(extra)],
                      "proposed": k, "accepted": a}
            ratio.observe(a / k, model=self.name)
            hist = self._spec_hist[s]
            hist.append((k, a))
            if len(hist) == hist.maxlen:
                pw = sum(p for p, _ in hist)
                aw = sum(acc for _, acc in hist)
                if pw > 0 and aw / pw < floor:
                    self.spec_disable(s, "acceptance_floor")
                    out[s]["fallback"] = "acceptance_floor"
        monitor.counter(
            "serving_decode_spec_proposed_total",
            "Draft tokens proposed to the verifier",
            labels=("model",)).inc(n_prop, model=self.name)
        monitor.counter(
            "serving_decode_spec_accepted_total",
            "Draft tokens the verifier accepted (the speedup is "
            "accepted + rounds extra tokens for 2 dispatches per round)",
            labels=("model",)).inc(n_acc, model=self.name)
        monitor.counter(
            "serving_decode_spec_rounds_total",
            "Speculative draft+verify rounds executed (2 dispatches "
            "each, emitting accepted+1 tokens per handled stream)",
            labels=("model",)).inc(model=self.name)
        return out

    def logits_full(self, tokens) -> np.ndarray:
        """(B, T) -> (B, T, V) float32 logits by full-sequence recompute
        (the parity oracle and the quantization-quality probe)."""
        out = self._logits_jit(self._params,
                               jnp.asarray(np.asarray(tokens, np.int32)))
        return np.asarray(out, np.float32)

    def close(self):
        """Release the page pools (the engine is retired; ~2 * L * P *
        page_size * H * D * dtype bytes come back)."""
        self._closed = True
        self._kpool = self._vpool = None
        self._params = None
        if self.spill is not None:
            self.spill.close()
        if self.draft is not None:
            self.draft.close()

    def describe(self) -> dict:
        d = self.cache.describe()
        d.update({"prefill_buckets": list(self.prefill_buckets),
                  "quantize": self.cfg.quantize,
                  "vocab_size": self.vocab,
                  "n_layers": self.n_layers,
                  "prefill_chunk_tokens": self.prefill_chunk_tokens})
        if self.draft is not None:
            d["spec"] = {"draft": self.cfg.spec_draft,
                         "k": int(self.cfg.spec_k),
                         "accept_floor": float(self.cfg.spec_accept_floor),
                         "window": int(self.cfg.spec_window),
                         "draft_pool": self.draft.cache.describe()}
        return d


# ==========================================================================
# The scheduler: iteration-level admission over one or more engines
# ==========================================================================
class _PrefillJob:
    """Admission-to-first-token state for one slot: the uncached suffix
    [pos, len(prompt)) still to prefill, executed in budgeted chunks
    between decode steps (head-of-line-free prefill)."""

    __slots__ = ("req", "pos", "chunks", "dpos", "tok")

    def __init__(self, req: GenerateRequest, pos: int,
                 dpos: Optional[int] = None):
        self.req = req
        self.pos = pos
        self.chunks = 0
        #: the speculative draft mirror's prefill cursor (None: stream
        #: has no draft slot); the job completes only when BOTH caches
        #: cover the prompt
        self.dpos = dpos
        #: the target's sampled first token, held until the draft mirror
        #: catches up (speculation needs both KV states at the prompt
        #: boundary before the stream's first round)
        self.tok: Optional[int] = None


class _EngineRun:
    """A live engine + the requests bound to its slots. `admitting` is
    True only for the newest engine; older runs drain and retire.
    `prefill` holds slots whose suffix prefill is still chunking (FIFO:
    insertion order is admission order)."""

    __slots__ = ("engine", "version", "admitting", "slot_req", "prefill")

    def __init__(self, engine: DecodeEngine, version: int):
        self.engine = engine
        self.version = version
        self.admitting = True
        self.slot_req: Dict[int, GenerateRequest] = {}
        self.prefill: "OrderedDict[int, _PrefillJob]" = OrderedDict()


class DecodeScheduler:
    """The continuous-batching loop: admit between steps, step every
    engine with live slots, retire drained engines. One daemon thread;
    every device interaction happens on it."""

    def __init__(self, name: str, queue_limit: int = 64):
        self.name = name
        self.queue_limit = int(queue_limit)
        self._pending: deque = deque()
        self._plock = DiagnosedLock(
            "deeplearning4j_tpu.serving.decode.DecodeScheduler._plock")
        self._runs: List[_EngineRun] = []
        self._rlock = DiagnosedLock(
            "deeplearning4j_tpu.serving.decode.DecodeScheduler._rlock")
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._draining = False
        #: KV-fabric jobs (page export/import) marshalled onto the
        #: scheduler thread — the ONLY thread allowed to touch the
        #: donated device pools. Guarded by _plock; (fn, done, box)
        self._fabric: deque = deque()
        # goodput accounting: page-stall slot-seconds apportioned out of
        # the step window by _step_all (stalled/considered share of each
        # step's wall) — read by _loop, only meaningful under the ledger
        self._stall_s = 0.0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"DecodeScheduler-{name}")
        self._started = False

    # -------------------------------------------------------------- control
    def install(self, engine: DecodeEngine, version: int):
        """Make `engine` the admitting engine; older runs stop admitting
        and retire once their in-flight sequences finish."""
        with self._rlock:
            for run in self._runs:
                run.admitting = False
            self._runs.append(_EngineRun(engine, version))
        if not self._started:
            self._started = True
            self._thread.start()
        self._wake.set()

    def submit(self, req: GenerateRequest):
        if self._draining or self._stop.is_set():
            raise ServerDrainingError(
                f"decode[{self.name}] is shutting down")
        with self._plock:
            if len(self._pending) >= self.queue_limit:
                monitor.counter("serving_decode_rejected_total",
                                "Generation requests rejected by "
                                "admission control",
                                labels=("model", "reason")).inc(
                    model=self.name, reason="queue_full")
                raise ServerOverloadedError(
                    f"decode[{self.name}]: join queue full "
                    f"({self.queue_limit} pending)")
            self._pending.append(req)
            depth = len(self._pending)
        monitor.gauge("serving_decode_queue_depth",
                      "Generation requests waiting for a decode slot",
                      labels=("model",)).set(depth, model=self.name)
        flight.note(req.ctx, "queued", depth=depth, model=self.name)
        self._wake.set()

    def run_fabric(self, fn, timeout: float = 30.0):
        """Run ``fn(engine)`` on the scheduler thread against the
        admitting engine and return its result. The device pools are
        donated by every compiled step, so any HTTP-thread work that
        reads or writes them (page export for a disaggregated transfer,
        shipment import) MUST marshal through here — the job executes
        between ticks, never concurrently with a step. Raises the job's
        own exception, or DeadlineExceededError if the loop never got
        to it within `timeout`."""
        if self._stop.is_set() or self._draining:
            raise ServerDrainingError(
                f"decode[{self.name}] is shutting down")
        box: dict = {}
        done = threading.Event()
        with self._plock:
            self._fabric.append((fn, done, box))
        self._wake.set()
        if not done.wait(timeout):
            raise DeadlineExceededError(
                f"decode[{self.name}]: fabric job did not run within "
                f"{timeout}s (scheduler saturated or stopped)")
        if "exc" in box:
            raise box["exc"]
        return box.get("res")

    def _fabric_tick(self) -> bool:
        """Drain queued fabric jobs on the scheduler thread. A job's
        failure belongs to its submitting thread (delivered through the
        box), never to the loop."""
        if not self._fabric:
            # unlocked empty-check on the common per-pass path: deque
            # reads are atomic under the GIL, and a submit racing this
            # pass sets _wake — the NEXT pass drains it. Skipping the
            # lock keeps the fabric free for the two hot schedulers of
            # an interference pair (no extra GIL handoff per pass)
            return False
        worked = False
        while True:
            with self._plock:
                if not self._fabric:
                    return worked
                fn, done, box = self._fabric.popleft()
            with self._rlock:
                engine = self._runs[-1].engine \
                    if self._runs and self._runs[-1].admitting else None
            try:
                if engine is None:
                    raise ServerDrainingError(
                        f"decode[{self.name}]: no admitting engine for "
                        "fabric job")
                box["res"] = fn(engine)
            except Exception as e:  # noqa: BLE001 — surfaced to the
                # submitting thread via the box; the scheduler loop
                # must outlive any single job's corrupt shipment
                box["exc"] = e
            done.set()
            worked = True

    def queue_state(self) -> Tuple[int, int]:
        with self._plock:
            return len(self._pending), self.queue_limit

    def inflight(self) -> int:
        with self._rlock:
            return sum(len(r.slot_req) + len(r.prefill)
                       for r in self._runs)

    def admitting_engine(self) -> Optional[DecodeEngine]:
        with self._rlock:
            if self._runs and self._runs[-1].admitting:
                return self._runs[-1].engine
            return None

    # --------------------------------------------------------------- loop
    def _loop(self):
        from deeplearning4j_tpu.monitor import goodput
        crash: Optional[Exception] = None
        while not self._stop.is_set():
            # goodput split of the scheduler pass: admission vs the
            # compute window (prefill + step + retire) with the step's
            # page-stall share apportioned out, vs idle wait below.
            # Zero-cost while the ledger is off: one flag check per pass
            gp = goodput.goodput_enabled()
            t_pass = time.perf_counter() if gp else 0.0
            try:
                worked = self._admit()
                worked = self._fabric_tick() or worked
                t_admitted = time.perf_counter() if gp else 0.0
                stall0 = self._stall_s
                worked = self._prefill_tick() or worked
                worked = self._step_all() or worked
                self._retire()
            except Exception as e:      # noqa: BLE001 — the scheduler
                # thread is the only place slots are reclaimed: an
                # unguarded exception here would strand every stream
                # forever while the servable still reported "ready".
                # Fail everything loudly and stop instead.
                crash = e
                log.exception("decode[%s]: scheduler crashed; failing "
                              "all streams", self.name)
                self._stop.set()
                break
            if gp:
                t_end = time.perf_counter()
                stall = max(self._stall_s - stall0, 0.0)
                goodput.decode_note(self.name, "admission",
                                    t_admitted - t_pass)
                goodput.decode_note(self.name, "page_stall", stall)
                goodput.decode_note(
                    self.name, "step_compute",
                    max(t_end - t_admitted - stall, 0.0))
            if not worked:
                idle0 = time.perf_counter() if gp else 0.0
                self._wake.wait(0.005)
                self._wake.clear()
                if gp:
                    goodput.decode_note(self.name, "idle",
                                        time.perf_counter() - idle0)
        # teardown: everything still live gets a terminal error
        exc = crash if crash is not None else ServerDrainingError(
            f"decode[{self.name}] shut down mid-stream")
        with self._rlock:
            runs = list(self._runs)
            self._runs.clear()
        for run in runs:
            for slot, job in run.prefill.items():
                run.engine.release_slot(slot)
                job.req.fail(exc)
            for slot, req in run.slot_req.items():
                run.engine.release_slot(slot)
                req.fail(exc)
            run.engine.close()
        self._fail_pending(crash if crash is not None
                           else ServerDrainingError(
                               f"decode[{self.name}] shut down"))
        self._fail_fabric(exc)

    def _fail_fabric(self, exc: Exception):
        while True:
            with self._plock:
                if not self._fabric:
                    return
                _fn, done, box = self._fabric.popleft()
            box["exc"] = exc
            done.set()

    def _fail_pending(self, exc: Exception):
        while True:
            with self._plock:
                if not self._pending:
                    return
                req = self._pending.popleft()
            req.fail(exc)

    def _admit(self) -> bool:
        with self._rlock:
            run = self._runs[-1] if self._runs and self._runs[-1].admitting \
                else None
        if run is None:
            return False
        worked = False
        while True:
            with self._plock:
                req = self._pending[0] if self._pending else None
            if req is None:
                break
            if req.cancelled.is_set():
                self._pop(req)
                req.finish("cancelled")
                continue
            if req.deadline is not None \
                    and time.monotonic() > req.deadline:
                self._pop(req)
                monitor.counter("serving_decode_rejected_total",
                                "Generation requests rejected by "
                                "admission control",
                                labels=("model", "reason")).inc(
                    model=self.name, reason="deadline")
                req.fail(DeadlineExceededError(
                    f"decode[{self.name}]: deadline expired after "
                    f"{time.monotonic() - req.enqueued:.3f}s in queue"))
                continue
            if len(req.prompt) >= run.engine.max_context:
                # the admitting engine changed under the request (a swap
                # to a shorter-context model raced generate()'s check):
                # fail it cleanly, never let admit() overrun a page table
                self._pop(req)
                req.fail(ValueError(
                    f"decode[{self.name}]: prompt length "
                    f"{len(req.prompt)} leaves no room to generate "
                    f"(live max_context {run.engine.max_context})"))
                continue
            try:
                info = run.engine.admit_prompt(req.prompt)
            except Exception as e:          # noqa: BLE001 — surfaced to req
                self._pop(req)
                log.exception("decode[%s]: admission failed", self.name)
                req.fail(e)
                continue
            if info is None:
                break                       # no slot/pages; retry next tick
            self._pop(req)
            # admission is now CHEAP (page-table writes + at most one COW
            # page copy; the suffix prefill runs in budgeted chunks on
            # the next _prefill_tick), so this loop keeps draining the
            # join queue until slots, pages or the queue are exhausted —
            # when a token step frees several slots at once, a burst of
            # queued joins lands in ONE tick, not one per step
            slot = info.slot
            req.cached_tokens = int(info.cached_len)
            # "joined a RUNNING batch" counts decoding streams only —
            # same-burst admissions still mid-prefill are not a batch
            # this request preempted into (inflight() would count them
            # and let the smoke's joins>0 gate pass on a workload where
            # continuous batching never engaged)
            with self._rlock:
                joined_running = any(r.slot_req for r in self._runs)
            if flight.enabled():
                # admission wait + the engine generation whose params
                # will write this stream's KV (the swap-generation fact
                # a postmortem needs) + how much prefill the prefix
                # cache just made free
                flight.note(req.ctx, "admitted", slot=slot,
                            engine_version=run.version,
                            wait_ms=round(
                                (time.monotonic() - req.enqueued) * 1e3,
                                3),
                            joined_running=joined_running,
                            cached_tokens=int(info.cached_len),
                            cow=info.cow_src is not None,
                            model=self.name)
            req.version = run.version
            run.prefill[slot] = _PrefillJob(
                req, int(info.cached_len),
                run.engine.draft_prefill_origin(slot))
            if joined_running:
                monitor.counter(
                    "serving_decode_preempted_joins_total",
                    "Requests admitted into an already-running batch "
                    "between token steps (continuous batching)",
                    labels=("model",)).inc(model=self.name)
            worked = True
        with self._plock:
            depth = len(self._pending)
        monitor.gauge("serving_decode_queue_depth",
                      "Generation requests waiting for a decode slot",
                      labels=("model",)).set(depth, model=self.name)
        return worked

    def _pop(self, req: GenerateRequest):
        with self._plock:
            if self._pending and self._pending[0] is req:
                self._pending.popleft()

    def _prefill_tick(self) -> bool:
        """Advance every in-flight prefill by at most the engine's
        per-tick token budget (FIFO across that engine's jobs), then
        return to the loop so a decode step can interleave — a long
        prompt costs the running streams one bounded chunk of ITL, never
        its whole prefill. Chunking off (budget 0) completes each job in
        a single program call. The final chunk yields the first token."""
        with self._rlock:
            runs = [r for r in self._runs if r.prefill]
        worked = False
        for run in runs:
            budget = run.engine.prefill_chunk_tokens
            spent = 0
            for slot in list(run.prefill.keys()):
                job = run.prefill.get(slot)
                if job is None:
                    continue
                req = job.req
                if req.cancelled.is_set():
                    run.prefill.pop(slot, None)
                    self._finish(run, slot, req, "cancelled")
                    worked = True
                    continue
                if req.deadline is not None \
                        and time.monotonic() > req.deadline:
                    run.prefill.pop(slot, None)
                    self._finish(run, slot, req, "deadline")
                    worked = True
                    continue
                total = len(req.prompt)
                try:
                    # bind the stream's context so prefill spans (and any
                    # first-compile ledger capture inside) carry its
                    # trace_id
                    with monitor.bind_context(req.ctx):
                        while job.pos < total:
                            if budget > 0 and spent >= budget:
                                break
                            n = total - job.pos if budget <= 0 \
                                else min(total - job.pos, budget - spent)
                            if job.pos == 0 and n == total:
                                # cold, whole prompt within budget: the
                                # dense program (bitwise the pre-cache
                                # path; also what cache-off runs)
                                tok, _ = run.engine.prefill(
                                    slot, req.prompt, req.temperature,
                                    req.top_k)
                            else:
                                tok = run.engine.prefill_chunk(
                                    slot, req.prompt, job.pos, n,
                                    req.temperature, req.top_k)
                            job.pos += n
                            job.chunks += 1
                            spent += n
                            worked = True
                            if job.pos >= total:
                                job.tok = tok
                except Exception as e:  # noqa: BLE001 — surfaced to req
                    run.prefill.pop(slot, None)
                    run.engine.release_slot(slot)
                    log.exception("decode[%s]: prefill failed", self.name)
                    req.fail(e)
                    continue
                # the speculative draft mirror prefills under the same
                # per-tick budget; its failure never fails the stream —
                # speculation just turns off and the stream decodes plain
                try:
                    with monitor.bind_context(req.ctx):
                        while job.dpos is not None and job.dpos < total:
                            if budget > 0 and spent >= budget:
                                break
                            n = total - job.dpos if budget <= 0 \
                                else min(total - job.dpos,
                                         budget - spent)
                            run.engine.draft_prefill(
                                slot, req.prompt, job.dpos, n,
                                req.temperature, req.top_k)
                            job.dpos += n
                            spent += n
                            worked = True
                except Exception:  # noqa: BLE001 — draft is optional
                    log.exception("decode[%s]: draft prefill failed; "
                                  "stream decodes plain", self.name)
                    run.engine.spec_disable(slot, "draft_prefill")
                    job.dpos = None
                if job.pos >= total and (job.dpos is None
                                         or job.dpos >= total):
                    run.prefill.pop(slot, None)
                    req.prefill_chunks = job.chunks
                    # prefill complete: every mapped prompt page holds
                    # final K/V — only now may the prefix index share it
                    run.engine.cache.register_prefix(slot, req.prompt)
                    run.engine.draft_prefill_done(slot, req.prompt)
                    run.slot_req[slot] = req
                    monitor.histogram(
                        "serving_decode_prefill_chunks",
                        "Prefill program executions per admission "
                        "(1 = unchunked; higher = budgeted chunking "
                        "interleaved with decode steps)",
                        labels=("model",),
                        buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32)
                    ).observe(job.chunks, model=self.name)
                    flight.note(req.ctx, "prefill_done",
                                chunks=job.chunks,
                                cached_tokens=req.cached_tokens,
                                model=self.name)
                    self._emit(run, slot, req, job.tok)
        return worked

    def _emit(self, run: _EngineRun, slot: int, req: GenerateRequest,
              tok: int):
        """Deliver one sampled token; finish/free the slot on EOS, the
        token budget, cancellation or the deadline."""
        if req.cancelled.is_set():
            self._finish(run, slot, req, "cancelled")
            return
        if req.deadline is not None and time.monotonic() > req.deadline:
            self._finish(run, slot, req, "deadline")
            return
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(run, slot, req, "eos")
            return
        exemplar = None if req.ctx is None else req.ctx.trace_id
        if req.last_emit_at is not None:
            if monitor.tracing_enabled() and req._last_pc is not None:
                # one span per inter-token gap, under the stream's ctx:
                # the merged trace shows every ITL stall of a slow p99
                # stream (the runbook's page-stall walk)
                monitor.add_span("decode/itl_gap", req._last_pc,
                                 time.perf_counter(), ctx=req.ctx,
                                 model=self.name, index=req.n_emitted)
            monitor.histogram(
                "serving_decode_inter_token_seconds",
                "Gap between consecutive streamed tokens of one request",
                labels=("model",), buckets=_ITL_BUCKETS).observe(
                time.monotonic() - req.last_emit_at, model=self.name,
                exemplar=exemplar)
        elif req.n_emitted == 0:
            # TTFT observed only for generations that actually deliver a
            # first token — cancelled/deadline admissions (checked above)
            # must not pollute the gated decode_ttft_p99_ms series
            monitor.histogram(
                "serving_decode_ttft_seconds",
                "Time from request arrival to its first generated token",
                labels=("model",), buckets=_TTFT_BUCKETS).observe(
                time.monotonic() - req.enqueued, model=self.name,
                exemplar=exemplar)
        req.emit(tok)
        monitor.counter("serving_decode_tokens_total",
                        "Generated tokens streamed to clients",
                        labels=("model",)).inc(model=self.name)
        if req.n_emitted >= req.max_new_tokens:
            self._finish(run, slot, req, "length")

    def _finish(self, run: _EngineRun, slot: int, req: GenerateRequest,
                reason: str):
        run.engine.release_slot(slot)
        run.slot_req.pop(slot, None)
        req.finish(reason)
        if monitor.tracing_enabled():
            # the whole stream as one span on the scheduler track, under
            # the stream's trace_id — queue wait + prefill + every token
            monitor.add_span("serving/stream", req.t0_pc,
                             time.perf_counter(), ctx=req.ctx,
                             model=self.name, reason=reason,
                             tokens=req.n_emitted,
                             engine_version=run.version)
        flight.note(req.ctx, "finish", reason=reason,
                    tokens=req.n_emitted,
                    spec_proposed=req.spec_proposed,
                    spec_accepted=req.spec_accepted, model=self.name)
        monitor.counter("serving_decode_finished_total",
                        "Finished generations by reason",
                        labels=("model", "reason")).inc(
            model=self.name, reason=reason)

    def _step_all(self) -> bool:
        from deeplearning4j_tpu.monitor import goodput
        gp = goodput.goodput_enabled()
        with self._rlock:
            runs = [r for r in self._runs if r.slot_req]
        worked = False
        for run in runs:
            # speculation first: eligible streams get an accepted burst
            # (draft propose + target verify, two dispatches for up to
            # k+1 tokens each); everything speculation did not handle
            # falls through to the plain one-token step below
            spec = run.engine.spec_step(exclude=run.prefill.keys()) \
                if run.engine.spec_enabled else {}
            for slot, res in spec.items():
                req = run.slot_req.get(slot)
                if req is None:
                    continue
                req.spec_rounds += 1
                req.spec_proposed += res["proposed"]
                req.spec_accepted += res["accepted"]
                if res.get("fallback") and flight.enabled():
                    flight.note(req.ctx, "spec_fallback",
                                reason=res["fallback"], slot=slot,
                                proposed=req.spec_proposed,
                                accepted=req.spec_accepted,
                                model=self.name)
                for tok in res["tokens"]:
                    self._emit(run, slot, req, tok)
                    if req.done.is_set():
                        break
            if spec:
                worked = True
            handled = set(spec)
            if not any(s not in handled for s in run.slot_req):
                continue
            step_t0 = time.perf_counter() if gp else 0.0
            toks, act, _ = run.engine.step(
                exclude=set(run.prefill.keys()) | handled)
            considered = stalled = 0
            for slot, req in list(run.slot_req.items()):
                if slot in handled:
                    continue
                considered += 1
                if act[slot]:
                    self._emit(run, slot, req, int(toks[slot]))
                elif int(run.engine.cache.seq_lens[slot]) \
                        >= run.engine.max_context:
                    self._finish(run, slot, req, "length_cap")
                elif req.cancelled.is_set():
                    # a page-stalled slot must still honor cancellation/
                    # deadline: releasing it is what refills the pool —
                    # otherwise an oversubscribed pool where EVERY slot
                    # stalls deadlocks forever with all pages leaked
                    self._finish(run, slot, req, "cancelled")
                elif req.deadline is not None \
                        and time.monotonic() > req.deadline:
                    self._finish(run, slot, req, "deadline")
                else:
                    # page-stalled this step (metered by the cache); the
                    # per-stream timeline needs the stall itself — it is
                    # THE explanation for an ITL-gap span in the trace
                    stalled += 1
                    if flight.enabled():
                        flight.note(req.ctx, "page_stall", slot=slot,
                                    seq_len=int(
                                        run.engine.cache.seq_lens[slot]),
                                    model=self.name)
            if gp and considered:
                # the stalled slots' share of this step's wall is page-
                # stall time, not compute — _loop bills it separately
                self._stall_s += (time.perf_counter() - step_t0) \
                    * (stalled / considered)
            worked = True
        return worked

    def _retire(self):
        with self._rlock:
            keep = []
            for run in self._runs:
                if not run.admitting and not run.slot_req \
                        and not run.prefill:
                    run.engine.close()
                    log.info("decode[%s]: retired engine v%d (drained)",
                             self.name, run.version)
                else:
                    keep.append(run)
            self._runs = keep

    # -------------------------------------------------------------- drain
    def drain(self, timeout: float = 30.0) -> bool:
        """Stop admitting, let in-flight sequences finish (bounded), then
        stop the loop. Queued joins fail with a draining error."""
        self._draining = True
        self._fail_pending(ServerDrainingError(
            f"decode[{self.name}] is draining"))
        deadline = time.monotonic() + timeout
        while self.inflight() and time.monotonic() < deadline:
            time.sleep(0.01)
        flushed = self.inflight() == 0
        self._stop.set()
        self._wake.set()
        if self._started:
            self._thread.join(timeout=max(0.1,
                                          deadline - time.monotonic() + 5))
        return flushed


# ==========================================================================
# The servable: versions + engine lifecycle behind the registry surface
# ==========================================================================
class ServedLM:
    """One named decode servable: version history + engine + scheduler.

    The LM sibling of registry.ServedModel — same lifecycle surface
    (status/describe/swap/rollback/shutdown), so ModelRegistry, the HTTP
    server, the fleet supervisor and the router drive both kinds without
    caring which is which."""

    kind = "lm"

    def __init__(self, name: str, model, source: str,
                 decode: Optional[DecodeConfig] = None):
        from deeplearning4j_tpu.serving.registry import ServableVersion
        self.name = name
        self.cfg = decode if decode is not None else DecodeConfig()
        self.status = "loading"
        self._swap_lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.decode.ServedLM._swap_lock")
        self._state_lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.decode.ServedLM._state_lock")
        engine = DecodeEngine(model, self.cfg, name=name)
        engine.warm()
        self.vocab = engine.vocab
        self.max_context = engine.max_context
        self.scheduler = DecodeScheduler(name,
                                         queue_limit=self.cfg.queue_limit)
        self.scheduler.install(engine, version=1)
        self.versions: List[ServableVersion] = [
            ServableVersion(1, str(source), model)]
        self.active = 0
        self.active_info = self.versions[0].describe()
        self._engines: Dict[int, DecodeEngine] = {1: engine}
        self.status = "ready"
        monitor.gauge("serving_model_ready",
                      "1 while the servable is warmed and live",
                      labels=("model",)).set(1, model=name)

    # ---------------------------------------------------------- generation
    def generate(self, prompt, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None,
                 deadline: Optional[float] = None) -> GenerateRequest:
        """Validate + enqueue one generation; returns the live request
        whose `events` queue streams tokens. Raises ValueError (400),
        ServerOverloadedError (429) or ServerDrainingError (503)."""
        if self.status == "stopping":
            raise ServerDrainingError(
                f"decode[{self.name}] is draining")
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token id")
        if prompt.size >= self.max_context:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to generate "
                f"(max_context {self.max_context})")
        if prompt.min() < 0 or prompt.max() >= self.vocab:
            raise ValueError(
                f"prompt ids must be in [0, {self.vocab}); got "
                f"[{int(prompt.min())}, {int(prompt.max())}]")
        if max_new_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        max_new = min(int(max_new_tokens), self.cfg.max_new_tokens_cap,
                      self.max_context - int(prompt.size))
        req = GenerateRequest(
            prompt, max_new_tokens=max_new, temperature=temperature,
            top_k=top_k, eos_id=eos_id,
            deadline=None if deadline is None
            else time.monotonic() + float(deadline))
        self.scheduler.submit(req)
        return req

    # ------------------------------------------------------------ kv fabric
    def export_prefix(self, prompt, timeout: float = 30.0) -> bytes:
        """Serialize the KV pages covering `prompt`'s full blocks into a
        framed transfer blob (the prefill half of disaggregation). If the
        prefix isn't cached yet, a one-token greedy generation prefills
        and retains it first; the page reads are marshalled onto the
        scheduler thread via run_fabric."""
        if self.status == "stopping":
            raise ServerDrainingError(f"decode[{self.name}] is draining")
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        engine = self.scheduler.admitting_engine()
        if engine is None:
            raise ServerDrainingError(
                f"decode[{self.name}]: no admitting engine")
        if not engine.cfg.prefix_cache:
            raise ValueError(
                f"decode[{self.name}]: prefix cache disabled; nothing "
                "to export")
        ps = engine.cfg.page_size
        full = (int(prompt.size) // ps) * ps
        if full < ps:
            raise ValueError(
                f"prompt too short to export: {prompt.size} tokens "
                f"< one {ps}-token page")
        head = prompt[:full]
        if engine.cache.cached_prefix_len(head) < full:
            req = self.generate(head, max_new_tokens=1, temperature=0.0,
                                deadline=timeout)
            while True:
                kind, payload = req.events.get(timeout=timeout)
                if kind == "done":
                    break
                if kind == "error":
                    raise payload
        frames = self.scheduler.run_fabric(
            lambda eng: eng.export_pages(head), timeout=timeout)
        return kvfabric.pack_transfer(np.asarray(head, np.int32), frames,
                                      ps)

    def import_prefix(self, payload: bytes, timeout: float = 30.0) -> dict:
        """Land a framed page transfer (produced by a prefill replica's
        export_prefix) into this servable's prefix cache. Frame integrity
        and geometry are verified before any pool write; a bad shipment
        raises kvfabric.FrameError and leaves the cache untouched."""
        if self.status == "stopping":
            raise ServerDrainingError(f"decode[{self.name}] is draining")
        tokens, frames, hdr = kvfabric.unpack_transfer(payload)
        engine = self.scheduler.admitting_engine()
        if engine is None:
            raise ServerDrainingError(
                f"decode[{self.name}]: no admitting engine")
        if int(hdr["page_size"]) != int(engine.cfg.page_size):
            raise kvfabric.FrameError(
                f"transfer page_size {hdr['page_size']} != "
                f"{engine.cfg.page_size} on decode[{self.name}]")
        if not engine.cfg.prefix_cache:
            raise ValueError(
                f"decode[{self.name}]: prefix cache disabled; cannot "
                "adopt pages")
        adopted = self.scheduler.run_fabric(
            lambda eng: eng.import_pages(tokens, frames), timeout=timeout)
        return {"adopted": int(adopted), "pages": len(frames),
                "tokens": int(np.asarray(tokens).size)}

    # ------------------------------------------------------------ lifecycle
    def _activate(self, sv, variant: Optional[str]):
        """Warm a full replacement engine off-path, then roll admissions
        onto it; in-flight sequences finish on their own engine (KV pages
        are only meaningful under the params that wrote them). `variant`
        is the source's parsed ``@`` suffix (quantize mode or ``spec``
        options); None keeps the servable's config as deployed."""
        from deeplearning4j_tpu.serving.registry import ModelLoadError
        cfg = apply_variant(self.cfg, variant) \
            if variant is not None else self.cfg
        t0 = time.perf_counter()
        engine = DecodeEngine(sv.model, cfg, name=self.name)
        if engine.vocab != self.vocab:
            engine.close()
            raise ModelLoadError(
                f"swap rejected: {sv.source!r} has vocab "
                f"{engine.vocab}, live servable {self.name!r} serves "
                f"{self.vocab} (deploy under a new name)")
        with monitor.span("serving/swap", model=self.name,
                          version=sv.version):
            engine.warm()
            self.scheduler.install(engine, version=sv.version)
        self._engines[sv.version] = engine
        if engine.max_context != self.max_context:
            # a swap may change KV capacity (cfg.max_context=None derives
            # it from the model); generate() must validate against the
            # LIVE admitting engine, and the scheduler re-checks at
            # admission for requests that raced this update
            log.warning("decode[%s]: max_context %d -> %d across swap",
                        self.name, self.max_context, engine.max_context)
            self.max_context = engine.max_context
        monitor.histogram("serving_swap_seconds",
                          "Load+warm+swap duration (off the request path)",
                          labels=("model",),
                          buckets=(0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120)
                          ).observe(time.perf_counter() - t0,
                                    model=self.name)

    def swap(self, source, keep_versions: int = 3) -> dict:
        from deeplearning4j_tpu.serving.registry import (
            ServableVersion, load_servable,
        )
        base, variant = parse_variant(str(source))
        model = load_servable(base)
        with self._swap_lock:
            if self.status == "stopping":
                raise ServerDrainingError(
                    f"decode[{self.name}] is draining; swap rejected")
            with self._state_lock:
                next_version = self.versions[-1].version + 1
            sv = ServableVersion(next_version, str(source), model)
            self._activate(sv, variant)
            with self._state_lock:
                self.versions.append(sv)
                self.active = len(self.versions) - 1
                while len(self.versions) > keep_versions:
                    dropped = self.versions.pop(0)
                    self.active -= 1
                    self._engines.pop(dropped.version, None)
                    log.info("decode[%s]: retired v%d (%s) from memory",
                             self.name, dropped.version, dropped.source)
                self.active_info = sv.describe()
            monitor.counter("serving_swaps_total",
                            "Zero-downtime model hot-swaps",
                            labels=("model",)).inc(model=self.name)
        log.info("decode[%s]: now admitting on v%d (%s); older versions "
                 "drain in place", self.name, sv.version, sv.source)
        return sv.describe()

    def rollback(self) -> dict:
        from deeplearning4j_tpu.serving.registry import ModelLoadError
        with self._swap_lock:
            if self.status == "stopping":
                raise ServerDrainingError(
                    f"decode[{self.name}] is draining; rollback rejected")
            with self._state_lock:
                if self.active == 0:
                    raise ModelLoadError(
                        f"decode[{self.name}]: no previous version in "
                        "memory to roll back to")
                sv = self.versions[self.active - 1]
            # the rolled-back-to version gets a FRESH warmed engine (its
            # old one may already be retired); the same rolling handoff
            base, variant = parse_variant(str(sv.source))
            self._activate(sv, variant)
            with self._state_lock:
                self.active -= 1
                self.active_info = sv.describe()
            monitor.counter("serving_rollbacks_total",
                            "One-step version rollbacks",
                            labels=("model",)).inc(model=self.name)
        log.warning("decode[%s]: rolled back to v%d (%s)", self.name,
                    sv.version, sv.source)
        return sv.describe()

    # --------------------------------------------------------------- admin
    def queue_state(self) -> Tuple[int, int]:
        """(depth, limit) of the join queue — the Retry-After input."""
        return self.scheduler.queue_state()

    def describe(self) -> dict:
        with self._state_lock:
            newest = self.scheduler.admitting_engine()
            d = {
                "name": self.name,
                "kind": self.kind,
                "status": self.status,
                "vocab_size": self.vocab,
                "max_context": self.max_context,
                "active_version": self.versions[self.active].version,
                "versions": [v.describe() for v in self.versions],
                "pending": self.scheduler.queue_state()[0],
                "inflight": self.scheduler.inflight(),
            }
            if newest is not None:
                d["decode"] = newest.describe()
            return d

    def shutdown(self, drain: bool = True, timeout: float = 30.0):
        self.status = "stopping"
        monitor.gauge("serving_model_ready",
                      "1 while the servable is warmed and live",
                      labels=("model",)).set(0, model=self.name)
        self.scheduler.drain(timeout=timeout if drain else 0.1)
