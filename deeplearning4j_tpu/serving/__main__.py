"""`python -m deeplearning4j_tpu.serving` — model-serving entrypoint
(TensorFlow-Serving-style servable host; see serving/cli.py)."""
from deeplearning4j_tpu.serving.cli import main

raise SystemExit(main())
