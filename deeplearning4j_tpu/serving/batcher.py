"""Shape-bucketed dynamic batcher — the serving-path compile stabilizer.

XLA compiles one program per input shape, and on the request path that is
fatal: a serving front end sees every batch size from 1 to whatever the
coalescing window produced, so a naive batcher pays a multi-second compile
on the first occurrence of EVERY size — exactly when a user is waiting.
TensorFlow-Serving solves this with servable warmup, Clipper with adaptive
batching; this module does both:

- **Bucket ladder.** Coalesced request groups are padded up to a fixed,
  configurable ladder of batch sizes (default 1/8/32/128). The jitted
  forward therefore only ever sees `len(buckets)` distinct shapes, each
  compiled at most once per model version. Oversized groups are chunked
  into max-bucket pieces (still ladder shapes — never a novel compile).
- **AOT warmup.** `warm(run)` pushes a zeros batch of every bucket through
  the live execution path at model-load / pre-swap time, so all compiles
  happen before the first user request (ParallelInference.update_model
  calls it with the REPLACEMENT model's runner before the atomic swap).
- **Coalescing deadline.** The worker waits at most `max_delay_ms` from
  the first queued request before dispatching, bounding the latency cost
  of batching (Clipper's batching/SLO layering).
- **Admission control.** The request queue is bounded: a full queue raises
  `ServerOverloadedError` (the HTTP layer maps it to 429 backpressure),
  and a request whose deadline expired before dispatch gets
  `DeadlineExceededError` (-> 504), never silent tail-latency blowup.

The compile ledger is host-side truth for the at-most-once guarantee:
`serving_bucket_compiles_total{model,bucket}` increments only when a
bucket shape is executed for the first time in the current model
generation, and `serving_warmup_runs_total` counts warmup executions —
`compiles == warmups` on /metrics proves no request ever compiled.
"""
from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import flight
from deeplearning4j_tpu.monitor import xla as xla_ledger
from deeplearning4j_tpu.util.locks import DiagnosedLock

log = logging.getLogger("deeplearning4j_tpu")

#: default ladder — powers apart so padding waste stays < ~4x while the
#: compile count stays tiny; tune per model via docs/SERVING.md.
DEFAULT_BUCKETS = (1, 8, 32, 128)


class ServingError(RuntimeError):
    """Base class for request-path serving failures."""


class ServerOverloadedError(ServingError):
    """Admission control: the request queue is full (HTTP 429)."""


class DeadlineExceededError(ServingError):
    """The request's deadline expired before a result was ready (504)."""


class ServerDrainingError(ServingError):
    """The batcher is draining for shutdown; not accepting requests (503)."""


class _Request:
    __slots__ = ("x", "deadline", "event", "result", "error", "enqueued",
                 "ctx", "t0")

    def __init__(self, x, deadline: Optional[float]):
        self.x = x
        self.deadline = deadline        # absolute time.monotonic() or None
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.enqueued = time.monotonic()
        # the submitting thread's trace context (None while tracing and
        # the flight recorder are off — one thread-local read, no alloc)
        self.ctx = monitor.current_context()
        self.t0 = time.perf_counter()   # queue-wait span start


class ShapeBucketedBatcher:
    """Coalesce concurrent requests, pad to the bucket ladder, run once.

    `runner(x) -> np.ndarray` is the execution engine — in production the
    live `ParallelInference.output` (SEQUENTIAL mode, so this batcher owns
    ALL coalescing); any callable with that signature works in tests.

    Usage:
        b = ShapeBucketedBatcher(pi.output, input_shape=(28, 28, 1))
        b.warm()                       # AOT: compile every bucket now
        y = b.predict(x, deadline=0.5) # thread-safe
    """

    def __init__(self, runner: Callable, input_shape: Tuple[int, ...],
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_delay_ms: float = 5.0,
                 queue_limit: int = 256,
                 dtype="float32",
                 name: str = "default"):
        bs = sorted(set(int(b) for b in buckets))
        if not bs or bs[0] < 1:
            raise ValueError(f"bucket ladder must be positive ints: {buckets}")
        self.runner = runner
        self.input_shape = tuple(int(s) for s in input_shape)
        self.buckets = tuple(bs)
        self.max_delay = max(0.0, float(max_delay_ms)) / 1e3
        self.dtype = np.dtype(dtype)
        self.name = name
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._compiled: set = set()     # bucket sizes run in this generation
        self._gen_lock = DiagnosedLock(
            "deeplearning4j_tpu.serving.batcher.ShapeBucketedBatcher._gen_lock")
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._worker = threading.Thread(target=self._serve_loop, daemon=True,
                                        name=f"ServingBatcher-{name}")
        self._worker.start()

    # -------------------------------------------------------------- buckets
    def bucket_for(self, n: int) -> int:
        """Smallest ladder size >= n (the max bucket for oversized n —
        callers chunk)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _pad(self, x: np.ndarray, b: int) -> np.ndarray:
        if x.shape[0] == b:
            return x
        pad = np.zeros((b - x.shape[0],) + x.shape[1:], x.dtype)
        return np.concatenate([x, pad], axis=0)

    def _run_bucketed(self, x: np.ndarray, runner: Callable,
                      warmup: bool = False, ledger=None) -> np.ndarray:
        """Pad/chunk to ladder shapes, run, unpad. The ONLY call site of
        the runner — every execution goes through the compile ledger.
        `ledger` overrides the live generation's set: warm() builds the
        NEXT generation's ledger aside so concurrent requests against the
        still-live old model don't read a half-built one."""
        n = x.shape[0]
        outs, ofs = [], 0
        while ofs < n:
            take = min(n - ofs, self.buckets[-1])
            b = self.bucket_for(take)
            chunk = self._pad(np.asarray(x[ofs:ofs + take]), b)
            with self._gen_lock:
                seen = self._compiled if ledger is None else ledger
                first = b not in seen
                if first:
                    seen.add(b)
            if first:
                monitor.counter(
                    "serving_bucket_compiles_total",
                    "First executions of a bucket shape per model "
                    "generation (each implies one XLA compile)",
                    labels=("model", "bucket")).inc(
                        model=self.name, bucket=str(b))
                if not warmup:
                    # the flight timeline must show WHEN a live request
                    # paid a compile (the ledger-hit event)
                    flight.note(monitor.current_context(),
                                "bucket_compile", bucket=b,
                                model=self.name)
                    log.warning(
                        "serving[%s]: bucket %d first executed on the "
                        "REQUEST path (compile latency hits a live request) "
                        "— warm() was skipped or the ladder changed",
                        self.name, b)
            out_chunk = runner(chunk)[:take]
            if first and xla_ledger.enabled():
                # tie the ladder bucket to the compiled program the ledger
                # just captured inside the runner (ParallelInference
                # forwards land under domain "serving"). latest_record is
                # a shared slot that concurrent traffic can overwrite, and
                # the runner may pad the bucket up to its device mesh —
                # accept any record at least bucket-sized (best-effort
                # diagnostics; the record's own batch is in the line).
                rec = xla_ledger.latest_record("serving")
                if rec is not None and (rec.examples_per_call or 0) >= b:
                    log.info(
                        "serving[%s]: bucket %d -> program %s "
                        "(%s, batch %d as compiled, %.2f GFLOP/call, "
                        "HBM peak %s bytes, compile %.2fs)",
                        self.name, b, rec.fingerprint, rec.name,
                        rec.examples_per_call,
                        (rec.flops or 0.0) / 1e9,
                        rec.hbm_peak_bytes
                        if rec.hbm_peak_bytes is not None else "n/a",
                        rec.compile_seconds)
            outs.append(out_chunk)
            ofs += take
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def warm(self, run: Optional[Callable] = None):
        """AOT-compile every bucket by pushing zeros batches through the
        execution path. `run` overrides the live runner — update_model
        passes the replacement model's runner so warmup happens before
        the hot swap. The new generation's compile ledger is built aside
        and installed atomically on completion: requests still flowing to
        the OLD (fully compiled) model mid-warmup never observe a
        half-reset ledger, so the compile counter stays an exact
        one-inc-per-(generation, bucket) record."""
        runner = run if run is not None else self.runner
        fresh: set = set()
        t0 = time.perf_counter()
        for b in self.buckets:
            zeros = np.zeros((b,) + self.input_shape, self.dtype)
            with monitor.span("serving/warmup", model=self.name, bucket=b):
                self._run_bucketed(zeros, runner, warmup=True, ledger=fresh)
            monitor.counter("serving_warmup_runs_total",
                            "AOT warmup executions (one per bucket per "
                            "model generation)",
                            labels=("model",)).inc(model=self.name)
        with self._gen_lock:
            self._compiled = fresh
        monitor.histogram("serving_warmup_seconds",
                          "Full bucket-ladder warmup duration",
                          labels=("model",),
                          buckets=(0.1, 0.5, 1, 2.5, 5, 10, 30, 60, 120)
                          ).observe(time.perf_counter() - t0,
                                    model=self.name)

    # ------------------------------------------------------------------ API
    def predict(self, x, deadline: Optional[float] = None,
                timeout: float = 60.0) -> np.ndarray:
        """Synchronous bucketed inference; thread-safe. `deadline` is a
        per-request budget in seconds — expired requests fail with
        DeadlineExceededError instead of serving stale tail latency."""
        x = np.asarray(x, self.dtype)
        if x.shape[1:] != self.input_shape:
            raise ValueError(
                f"serving[{self.name}]: request shape {x.shape[1:]} does "
                f"not match model input {self.input_shape}")
        if x.shape[0] == 0:
            raise ValueError(
                f"serving[{self.name}]: empty request (0 examples)")
        if self._draining.is_set() or self._stop.is_set():
            raise ServerDrainingError(
                f"serving[{self.name}] is shutting down")
        req = _Request(x, None if deadline is None
                       else time.monotonic() + deadline)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            monitor.counter("serving_rejected_total",
                            "Requests rejected by admission control",
                            labels=("model", "reason")).inc(
                model=self.name, reason="queue_full")
            raise ServerOverloadedError(
                f"serving[{self.name}]: request queue full "
                f"({self._queue.maxsize} pending)")
        monitor.gauge("serving_queue_depth", "Queued serving requests",
                      labels=("model",)).set(self._queue.qsize(),
                                             model=self.name)
        wait = timeout if deadline is None else min(timeout, deadline + 1.0)
        if not req.event.wait(wait):
            req.error = req.error or DeadlineExceededError(
                f"serving[{self.name}]: no result within {wait:.1f}s")
            raise req.error
        if req.error is not None:
            raise req.error
        return req.result

    # ------------------------------------------------------------- worker
    def _coalesce(self, first: _Request):
        """Gather queued requests behind `first` until the max bucket is
        filled or the coalescing deadline from first-arrival passes."""
        reqs, total = [first], first.x.shape[0]
        deadline = time.monotonic() + self.max_delay
        while total < self.buckets[-1]:
            remaining = deadline - time.monotonic()
            try:
                nxt = self._queue.get(timeout=max(0.0, remaining)) \
                    if remaining > 0 else self._queue.get_nowait()
            except queue.Empty:
                break
            reqs.append(nxt)
            total += nxt.x.shape[0]
        return reqs

    def _serve_loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._draining.is_set():
                    break
                continue
            reqs = self._coalesce(first)
            now = time.monotonic()
            live = []
            for r in reqs:
                if r.deadline is not None and now > r.deadline:
                    r.error = DeadlineExceededError(
                        f"serving[{self.name}]: deadline expired after "
                        f"{now - r.enqueued:.3f}s in queue")
                    monitor.counter("serving_rejected_total",
                                    "Requests rejected by admission control",
                                    labels=("model", "reason")).inc(
                        model=self.name, reason="deadline")
                    r.event.set()
                else:
                    live.append(r)
            if not live:
                continue
            if monitor.tracing_enabled():
                # per-request queue-wait spans, recorded on behalf of
                # the submitting threads (their ctx, this thread's track)
                dispatch_pc = time.perf_counter()
                for r in live:
                    monitor.add_span("serving/queue_wait", r.t0,
                                     dispatch_pc, ctx=r.ctx,
                                     model=self.name)
            if flight.enabled():
                for r in live:
                    flight.note(r.ctx, "dispatch",
                                wait_ms=round(
                                    (now - r.enqueued) * 1e3, 3),
                                coalesced=len(live), model=self.name)
            try:
                batch = np.concatenate([r.x for r in live], axis=0) \
                    if len(live) > 1 else live[0].x
                monitor.histogram(
                    "serving_batch_size",
                    "Coalesced serving batch sizes (pre-padding examples)",
                    labels=("model",),
                    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512)
                ).observe(batch.shape[0], model=self.name)
                n, padded, ofs = batch.shape[0], 0, 0
                while ofs < n:          # chunks mirror _run_bucketed
                    take = min(n - ofs, self.buckets[-1])
                    padded += self.bucket_for(take)
                    ofs += take
                monitor.histogram(
                    "serving_batch_pad_fraction",
                    "Padding waste per device batch (padded/real - 1)",
                    labels=("model",),
                    buckets=(0.0, 0.1, 0.25, 0.5, 1.0, 3.0, 7.0)
                ).observe(padded / n - 1.0, model=self.name)
                # bind the FIRST coalesced request's context to this
                # worker for the batch extent: the batch span, the
                # ledger capture inside the runner, and any first-compile
                # note all land under one trace_id (the others are
                # linked through their queue_wait spans above)
                with monitor.bind_context(live[0].ctx):
                    with monitor.span("serving/batch", model=self.name,
                                      n=int(batch.shape[0]),
                                      requests=len(live)):
                        out = self._run_bucketed(batch, self.runner)
                ofs = 0
                for r in live:
                    r.result = out[ofs:ofs + r.x.shape[0]]
                    ofs += r.x.shape[0]
            except Exception as e:      # surface errors to all waiters
                for r in live:
                    r.error = e
            finally:
                for r in live:
                    r.event.set()
            monitor.gauge("serving_queue_depth", "Queued serving requests",
                          labels=("model",)).set(self._queue.qsize(),
                                                 model=self.name)
        # drain leftovers so no caller blocks forever
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            r.error = ServerDrainingError(
                f"serving[{self.name}] shut down")
            r.event.set()

    # --------------------------------------------------------------- admin
    def drain(self, timeout: float = 30.0) -> bool:
        """Stop accepting new requests, flush everything in flight, stop
        the worker. Returns True when the queue emptied in time."""
        self._draining.set()
        deadline = time.monotonic() + timeout
        while not self._queue.empty() and time.monotonic() < deadline:
            time.sleep(0.01)
        flushed = self._queue.empty()
        self._stop.set()
        self._worker.join(timeout=max(0.1, deadline - time.monotonic()))
        return flushed

    def shutdown(self):
        self._stop.set()
        self._draining.set()
        self._worker.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
