"""`python -m deeplearning4j_tpu.serving` — the serve CLI entrypoint.

Stands up a ModelServer over one or more servables and runs until
SIGTERM/SIGINT, then drains gracefully (stop admitting, flush in-flight,
clean exit 0) — the deploy surface a process supervisor or container
runtime manages.

Usage:
    python -m deeplearning4j_tpu.serving \
        --model lenet=zoo:LeNet --port 8500 \
        --buckets 1,8,32,128 --max-delay-ms 5 --deadline-s 30

    # serve a training run's newest verified checkpoint:
    python -m deeplearning4j_tpu.serving --model prod=/ckpts/run17

See docs/SERVING.md for the API, bucket-ladder tuning, and the
swap/rollback runbook.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.serving",
        description="Production model server: versioned registry, "
                    "shape-bucketed AOT-warmed batching, admission "
                    "control, zero-downtime hot-swap (docs/SERVING.md)")
    p.add_argument("--model", action="append", required=True,
                   metavar="NAME=SOURCE",
                   help="servable to deploy; SOURCE is a checkpoint dir "
                        "(manifest.json), a model zip, a Keras .h5, or "
                        "zoo:<Arch>. Repeatable.")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 behind a load balancer)")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--buckets", default="1,8,32,128",
                   help="batch-size bucket ladder (comma-separated)")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="batching coalescing deadline")
    p.add_argument("--queue-limit", type=int, default=256,
                   help="admission-control queue bound (full -> 429)")
    p.add_argument("--deadline-s", type=float, default=30.0,
                   help="default per-request deadline (expired -> 504)")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="max time to flush in-flight work on SIGTERM")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import os

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the axon TPU plugin force-appends itself to jax_platforms at
        # import, overriding the env var — pin the user's choice back
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from deeplearning4j_tpu.serving.registry import (
        ModelLoadError, ModelRegistry,
    )
    from deeplearning4j_tpu.serving.server import ModelServer

    try:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    except ValueError:
        raise SystemExit(f"--buckets must be comma-separated ints, got "
                         f"{args.buckets!r}")
    specs = []
    for spec in args.model:
        name, sep, source = spec.partition("=")
        if not sep or not name or not source:
            raise SystemExit(f"--model expects NAME=SOURCE, got {spec!r}")
        specs.append((name, source))

    registry = ModelRegistry()
    for name, source in specs:
        try:
            served = registry.deploy(name, source, buckets=buckets,
                                     max_delay_ms=args.max_delay_ms,
                                     queue_limit=args.queue_limit)
        except ModelLoadError as e:
            raise SystemExit(f"cannot deploy {name!r}: {e}")
        print(json.dumps({"deployed": name,
                          "input_shape": list(served.input_shape),
                          "buckets": list(served.batcher.buckets)}),
              file=sys.stderr)

    server = ModelServer(registry, host=args.host, port=args.port,
                         default_deadline_s=args.deadline_s)
    print(json.dumps({"serving": server.url,
                      "models": registry.names(),
                      "endpoints": ["/v1/models", "/healthz", "/readyz",
                                    "/metrics"]}))
    sys.stdout.flush()

    stop = threading.Event()

    def _on_signal(signum, frame):
        print(json.dumps({"signal": signum, "action": "drain"}),
              file=sys.stderr)
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_signal)
    stop.wait()
    server.drain(timeout=args.drain_timeout_s)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
