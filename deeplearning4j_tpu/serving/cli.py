"""`python -m deeplearning4j_tpu.serving` — the serve CLI entrypoint.

Single-replica mode (default): stands up a ModelServer over one or more
servables and runs until SIGTERM/SIGINT, then drains gracefully (stop
admitting, flush in-flight, clean exit 0) — the deploy surface a process
supervisor or container runtime manages.

Fleet mode (``--replicas N``, N >= 2): stands up a ReplicaSupervisor over
N serving replicas (subprocess by default — each its own crash domain —
or ``--replica-mode inprocess``) behind a ResilientRouter front end with
per-(replica, model) circuit breakers, priority-class shedding
(``--priority-classes``, ``X-Priority`` request header), and hedged
retries. ``--port`` is then the ROUTER's port; replicas bind ephemeral
ports on localhost.

Usage:
    python -m deeplearning4j_tpu.serving \
        --model lenet=zoo:LeNet --port 8500 \
        --buckets 1,8,32,128 --max-delay-ms 5 --deadline-s 30

    # serve a training run's newest verified checkpoint, fleet of 3:
    python -m deeplearning4j_tpu.serving --model prod=/ckpts/run17 \
        --replicas 3 --priority-classes interactive,standard,batch

See docs/SERVING.md for the API, bucket-ladder tuning, the swap/rollback
runbook, and the "Fleet operations" section for supervisor/router knobs.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.serving",
        description="Production model server: versioned registry, "
                    "shape-bucketed AOT-warmed batching, admission "
                    "control, zero-downtime hot-swap (docs/SERVING.md)")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=SOURCE",
                   help="predict servable to deploy; SOURCE is a "
                        "checkpoint dir (manifest.json), a model zip, a "
                        "Keras .h5, or zoo:<Arch> (constructor kwargs "
                        "ride a query string: zoo:LeNet?num_classes=10). "
                        "Repeatable.")
    # ----------------------------------------------------- decode (LM) mode
    dec = p.add_argument_group(
        "LM decode servables (docs/SERVING.md 'LLM decode')")
    dec.add_argument("--lm", action="append", default=[],
                     metavar="NAME=SOURCE",
                     help="decode servable (continuous-batching token "
                          "generation, POST .../generate). Same SOURCE "
                          "forms as --model; an @int8 / @bf16 suffix "
                          "serves a post-training-quantized variant "
                          "(e.g. zoo:TransformerLM?n_layers=2@int8) and "
                          "@spec[:draft=...,k=...] serves with "
                          "speculative decoding (draft-verify; greedy "
                          "output is unchanged). Repeatable.")
    dec.add_argument("--decode-slots", type=int, default=4,
                     help="fixed in-flight decode batch positions")
    dec.add_argument("--decode-page-size", type=int, default=16,
                     help="tokens per KV-cache page")
    dec.add_argument("--decode-max-context", type=int, default=None,
                     help="KV capacity per sequence (default: the "
                          "model's seq_length)")
    dec.add_argument("--decode-pool-pages", type=int, default=None,
                     help="physical KV pages in the pool (default "
                          "slots*max_context/page_size: no "
                          "oversubscription)")
    dec.add_argument("--decode-queue-limit", type=int, default=64,
                     help="pending-join bound (full -> 429)")
    dec.add_argument("--prefill-buckets", default=None,
                     help="prefill sequence-length ladder (comma ints, "
                          "page-aligned; default: geometric up to "
                          "max_context)")
    dec.add_argument("--prefill-chunk-tokens", type=int, default=None,
                     help="per-scheduler-tick prefill-token budget: long "
                          "uncached prompt suffixes run in chunks of at "
                          "most this many tokens BETWEEN decode steps, so "
                          "one long prompt cannot stall every stream's "
                          "inter-token latency (default: 4 pages; 0 "
                          "disables chunking)")
    dec.add_argument("--spec-draft", default=None, metavar="SRC",
                     help="turn on speculative decoding for every --lm "
                          "servable: 'int8'/'bf16' self-draft the target "
                          "through a quantized variant of its own "
                          "params; any other value loads a servable "
                          "source with the SAME vocab (mismatch is a "
                          "deploy-time error). Per-servable override: "
                          "the @spec source suffix")
    dec.add_argument("--spec-k", type=int, default=4,
                     help="draft tokens proposed per verify round")
    dec.add_argument("--spec-accept-floor", type=float, default=0.4,
                     help="rolling acceptance-rate floor below which a "
                          "stream stops speculating (plain decode)")
    dec.add_argument("--spec-window", type=int, default=8,
                     help="rounds in the per-stream acceptance window")
    dec.add_argument("--spec-draft-pool-pages", type=int, default=None,
                     help="KV pages in the draft engine's own pool "
                          "(default: sized like the target's)")
    dec.add_argument("--no-prefix-cache", action="store_true",
                     help="disable copy-on-write KV prefix sharing "
                          "(radix-indexed page reuse across requests "
                          "with a common prompt prefix; on by default — "
                          "greedy outputs are identical either way)")
    dec.add_argument("--kv-spill-pages", type=int, default=0,
                     help="host-RAM KV spill-tier capacity in pages (0 "
                          "disables): zero-ref retained prefix pages "
                          "demote into pinned host memory instead of "
                          "being dropped, and promote back into HBM on a "
                          "prefix hit (docs/SERVING.md 'Tiered KV "
                          "fabric')")
    dec.add_argument("--kv-role", choices=("prefill", "decode", "mixed"),
                     default="mixed",
                     help="disaggregation role this server advertises on "
                          "/readyz: 'prefill' computes KV and ships "
                          "pages, 'decode' streams tokens, 'mixed' does "
                          "both (single-replica mode; fleet mode assigns "
                          "roles with --kv-roles)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 behind a load balancer)")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--buckets", default="1,8,32,128",
                   help="batch-size bucket ladder (comma-separated)")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="batching coalescing deadline")
    p.add_argument("--queue-limit", type=int, default=256,
                   help="admission-control queue bound (full -> 429)")
    p.add_argument("--deadline-s", type=float, default=30.0,
                   help="default per-request deadline (expired -> 504)")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="max time to flush in-flight work on SIGTERM")
    p.add_argument("--enable-fault-injection", action="store_true",
                   help="expose POST /v1/faults (chaos testing; wedge "
                        "probes / predicts of THIS process) and honor "
                        "$DL4J_TPU_SERVING_FAULTS. Never on by default.")
    # -------------------------------------------- observability (tracing)
    obs = p.add_argument_group(
        "observability (docs/OBSERVABILITY.md 'Tracing a single request')")
    obs.add_argument("--trace-out", default=None, metavar="PATH",
                     help="enable span tracing and save the Chrome/"
                          "Perfetto trace here on drain. In fleet mode "
                          "the router writes PATH and each subprocess "
                          "replica writes PATH-stem.<replica>.json — "
                          "merge them with tools/trace_report.py")
    obs.add_argument("--postmortem-dir", default=None, metavar="DIR",
                     help="flight-recorder SLO postmortems (5xx, breaker "
                          "open, wedge, p99 breach) are auto-dumped here "
                          "as JSON")
    obs.add_argument("--flight-records", type=int, default=512,
                     help="per-request flight-recorder ring capacity")
    obs.add_argument("--no-flight", action="store_true",
                     help="disable the flight recorder (on by default "
                          "for served processes; the ring is bounded "
                          "host memory, never on the compiled path)")
    obs.add_argument("--slo-p99-ms", type=float, default=None,
                     help="latency SLO: 99%% of requests must finish "
                          "under this; breaches fire the SLO engine's "
                          "burn-rate alert (reason p99_breach) and an "
                          "automatic postmortem")
    obs.add_argument("--slo-availability", type=float, default=None,
                     metavar="TARGET",
                     help="availability SLO target (e.g. 0.999). Any "
                          "--slo-* flag enables the in-process time-"
                          "series ring + multi-window burn-rate "
                          "alerting; verdicts on GET /v1/slo, firings "
                          "trip flight postmortems")
    obs.add_argument("--slo-sample-interval-s", type=float, default=5.0,
                     help="time-series sampling interval while an "
                          "--slo-* objective is active")
    obs.add_argument("--slo-windows", default=None, metavar="FL,FS,SL,SS",
                     help="override the burn-rate windows (seconds): "
                          "fast-long,fast-short,slow-long,slow-short "
                          "(default 3600,300,21600,1800)")
    # ------------------------------------------------------ fleet mode
    fleet = p.add_argument_group(
        "fleet mode (docs/SERVING.md 'Fleet operations')")
    fleet.add_argument("--replicas", type=int, default=1,
                       help="N >= 2 supervises N replicas behind the "
                            "resilient router; 1 = plain single server")
    fleet.add_argument("--replica-mode", choices=("subprocess", "inprocess"),
                       default="subprocess",
                       help="subprocess = own crash domain per replica "
                            "(production); inprocess = threads (tests)")
    fleet.add_argument("--priority-classes",
                       default="interactive,standard,batch",
                       help="ordered priority ladder, highest first; "
                            "requests select via the X-Priority header")
    fleet.add_argument("--shed-floor", type=float, default=0.7,
                       help="fleet utilization at which the LOWEST class "
                            "starts shedding (higher classes shed at "
                            "evenly spaced higher thresholds)")
    fleet.add_argument("--per-replica-inflight", type=int, default=8,
                       help="router-side in-flight cap per replica (the "
                            "capacity unit behind shedding)")
    fleet.add_argument("--probe-interval-s", type=float, default=1.0)
    fleet.add_argument("--probe-timeout-s", type=float, default=2.0)
    fleet.add_argument("--unhealthy-after", type=int, default=3,
                       help="consecutive failed probes before a live "
                            "replica is presumed wedged and replaced")
    fleet.add_argument("--restart-budget", type=int, default=5,
                       help="restarts allowed per replica per 10 min "
                            "before it is marked dead (crash loop)")
    fleet.add_argument("--no-hedge", action="store_true",
                       help="disable hedged retries for straggler "
                            "predicts")
    fleet.add_argument("--kv-roles", default=None, metavar="R0,R1,...",
                       help="per-replica disaggregation roles (comma "
                            "list of prefill|decode|mixed, indexed by "
                            "replica); replicas beyond the list — "
                            "autoscaled ones included — serve 'mixed'. "
                            "At least one replica must be able to decode")
    fleet.add_argument("--no-affinity", action="store_true",
                       help="disable prefix-affinity routing (steering "
                            "same-prefix streams to the replica whose "
                            "heartbeat advertises ownership of the "
                            "prompt's leading KV block)")
    fleet.add_argument("--disagg-min-tokens", type=int, default=None,
                       help="prompts at least this many tokens long are "
                            "prefilled on a prefill-role replica and "
                            "their KV pages shipped to the decode "
                            "replica before the stream is routed "
                            "(default: disabled)")
    fleet.add_argument("--disagg-timeout-s", type=float, default=30.0,
                       help="per-leg timeout for the kv export/import "
                            "transfer; a missed deadline fails over to "
                            "local prefill on the decode replica")
    # ----------------------------------------------- continuous rollout
    ro = p.add_argument_group(
        "continuous rollout (docs/SERVING.md 'Continuous rollout')")
    ro.add_argument("--rollout-watch", default=None, metavar="DIR",
                    help="checkpoint directory to tail for new versions; "
                         "enables the RolloutController (fleet mode only)")
    ro.add_argument("--rollout-model", default=None,
                    help="served model name the rollout swaps (default: "
                         "the first --model/--lm name)")
    ro.add_argument("--rollout-mode", choices=("blessed", "latest"),
                    default="blessed",
                    help="tail the eval-gated blessed.json manifest "
                         "(default) or the raw newest manifest entry")
    ro.add_argument("--rollout-observe-s", type=float, default=30.0,
                    help="canary observation window before the verdict")
    ro.add_argument("--rollout-poll-s", type=float, default=5.0,
                    help="how often the watcher re-reads the manifest")
    ro.add_argument("--rollout-canary-fraction", type=float, default=0.1,
                    help="bounded share of live traffic routed to the "
                         "canary replica (0 < f <= 0.5)")
    ro.add_argument("--rollout-min-requests", type=int, default=20,
                    help="minimum canary requests before a promote "
                         "verdict (insufficient traffic rejects)")
    ro.add_argument("--rollout-p99-floor-ms", type=float, default=10.0,
                    help="p99 regressions below this floor are noise, "
                         "not a verdict; raise it where the canary's "
                         "first requests pay a compile (cold swap)")
    # ------------------------------------------------------- autoscaling
    asc = p.add_argument_group(
        "load-signal autoscaling (docs/SERVING.md 'Autoscaling')")
    asc.add_argument("--autoscale-max", type=int, default=None,
                     metavar="N",
                     help="enable autoscaling up to N replicas "
                          "(--replicas is the floor); scale signal is "
                          "router in-flight vs healthy capacity "
                          "(--per-replica-inflight)")
    asc.add_argument("--autoscale-high", type=float, default=0.8,
                     help="utilization above this for consecutive ticks "
                          "scales up")
    asc.add_argument("--autoscale-low", type=float, default=0.25,
                     help="utilization below this for consecutive ticks "
                          "drains one replica (readyz-confirmed drain, "
                          "never a kill)")
    asc.add_argument("--autoscale-cooldown-s", type=float, default=10.0,
                     help="minimum seconds between scaling decisions")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import os

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the axon TPU plugin force-appends itself to jax_platforms at
        # import, overriding the env var — pin the user's choice back
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        # same persistent-compile-cache convention as bench.py/conftest —
        # fleet replicas and chaos-restarted replicas skip recompiling
        # the bucket ladder
        jax.config.update("jax_compilation_cache_dir",
                          os.environ["JAX_COMPILATION_CACHE_DIR"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.monitor import flight as flight_recorder
    from deeplearning4j_tpu.serving.registry import (
        ModelLoadError, ModelRegistry,
    )
    from deeplearning4j_tpu.serving.server import ModelServer

    # observability defaults for served processes: the flight recorder is
    # ON (bounded host-side ring; the zero-cost contract only governs the
    # library default), span tracing only when --trace-out asks for it
    if not args.no_flight:
        flight_recorder.enable_flight(capacity=args.flight_records,
                                      dump_dir=args.postmortem_dir)
    if args.trace_out:
        monitor.enable_tracing()

    try:
        buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    except ValueError:
        raise SystemExit(f"--buckets must be comma-separated ints, got "
                         f"{args.buckets!r}")

    def parse_specs(values, flag):
        out = []
        for spec in values:
            name, sep, source = spec.partition("=")
            if not sep or not name or not source:
                raise SystemExit(f"{flag} expects NAME=SOURCE, got "
                                 f"{spec!r}")
            out.append((name, source))
        return out

    specs = parse_specs(args.model, "--model")
    lm_specs = parse_specs(args.lm, "--lm")
    if not specs and not lm_specs:
        raise SystemExit("deploy at least one servable (--model/--lm)")
    seen = set()
    for name, _ in specs + lm_specs:
        if name in seen:
            raise SystemExit(f"duplicate servable name {name!r}")
        seen.add(name)
    decode_cfg = _decode_config(args)

    if args.replicas > 1:
        return _main_fleet(args, specs, lm_specs, buckets, decode_cfg)

    registry = ModelRegistry()
    for name, source in specs:
        try:
            served = registry.deploy(name, source, buckets=buckets,
                                     max_delay_ms=args.max_delay_ms,
                                     queue_limit=args.queue_limit)
        except ModelLoadError as e:
            raise SystemExit(f"cannot deploy {name!r}: {e}")
        print(json.dumps({"deployed": name,
                          "input_shape": list(served.input_shape),
                          "buckets": list(served.batcher.buckets)}),
              file=sys.stderr)
    for name, source in lm_specs:
        try:
            served = registry.deploy_lm(name, source, decode=decode_cfg)
        except ModelLoadError as e:
            raise SystemExit(f"cannot deploy LM {name!r}: {e}")
        print(json.dumps({"deployed": name, "kind": "lm",
                          "vocab_size": served.vocab,
                          "max_context": served.max_context}),
              file=sys.stderr)

    from deeplearning4j_tpu.monitor import slo as slo_mod
    slo_engine = _slo_setup(args, slo_mod.server_objectives(
        slo_p99_ms=args.slo_p99_ms,
        availability_target=args.slo_availability))
    server = ModelServer(registry, host=args.host, port=args.port,
                         default_deadline_s=args.deadline_s,
                         enable_faults=args.enable_fault_injection,
                         slo_engine=slo_engine, kv_role=args.kv_role)
    endpoints = ["/v1/models", "/healthz", "/readyz", "/metrics"]
    if slo_engine is not None:
        endpoints += ["/v1/slo", "/v1/timeseries"]
    print(json.dumps({"serving": server.url,
                      "models": registry.names(),
                      "endpoints": endpoints}))
    sys.stdout.flush()

    stop = threading.Event()

    def _on_signal(signum, frame):
        print(json.dumps({"signal": signum, "action": "drain"}),
              file=sys.stderr)
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_signal)
    stop.wait()
    server.drain(timeout=args.drain_timeout_s)
    if args.trace_out:
        n = monitor.save_trace(args.trace_out)
        print(json.dumps({"trace_out": args.trace_out, "events": n}),
              file=sys.stderr)
    return 0


def _slo_enabled(args) -> bool:
    return (args.slo_availability is not None
            or args.slo_p99_ms is not None)


def _slo_setup(args, objectives):
    """Enable the time-series ring + SLO engine from --slo-* flags.
    Returns the engine (None when no --slo-* flag was given)."""
    if not objectives:
        return None
    from deeplearning4j_tpu.monitor import slo, timeseries
    rules = slo.DEFAULT_RULES
    if args.slo_windows:
        try:
            fl, fs, sl, ss = (float(x)
                              for x in args.slo_windows.split(","))
        except ValueError:
            raise SystemExit("--slo-windows expects 4 comma-separated "
                             f"seconds, got {args.slo_windows!r}")
        # keep the workbook burn thresholds, scale the flap-suppression
        # hold with the short windows
        rules = (slo.BurnRule("page", fl, fs, 14.4,
                              keep_firing_s=max(2.0, fs / 2)),
                 slo.BurnRule("ticket", sl, ss, 6.0,
                              keep_firing_s=max(2.0, ss / 2)))
    timeseries.enable_timeseries(interval_s=args.slo_sample_interval_s)
    return slo.enable_slo(objectives, rules=rules)


def _decode_config(args):
    """CLI decode knobs -> DecodeConfig (shared by all --lm servables)."""
    from deeplearning4j_tpu.serving.decode import DecodeConfig
    prefill = None
    if args.prefill_buckets:
        try:
            prefill = tuple(int(b) for b in args.prefill_buckets.split(",")
                            if b)
        except ValueError:
            raise SystemExit("--prefill-buckets must be comma-separated "
                             f"ints, got {args.prefill_buckets!r}")
    return DecodeConfig(slots=args.decode_slots,
                        page_size=args.decode_page_size,
                        max_context=args.decode_max_context,
                        pool_pages=args.decode_pool_pages,
                        prefill_buckets=prefill,
                        queue_limit=args.decode_queue_limit,
                        prefix_cache=not args.no_prefix_cache,
                        prefill_chunk_tokens=args.prefill_chunk_tokens,
                        spec_draft=args.spec_draft,
                        spec_k=args.spec_k,
                        spec_accept_floor=args.spec_accept_floor,
                        spec_window=args.spec_window,
                        spec_draft_pool_pages=args.spec_draft_pool_pages,
                        spill_pages=args.kv_spill_pages)


def _main_fleet(args, specs, lm_specs, buckets, decode_cfg) -> int:
    """--replicas N: supervisor + router. --port is the router's port."""
    import os

    from deeplearning4j_tpu.serving.fleet import (
        AutoscaleConfig, InProcessReplica, ReplicaSpec, ReplicaSupervisor,
        SubprocessReplica,
    )
    from deeplearning4j_tpu.serving.quantize import parse_variant
    from deeplearning4j_tpu.serving.router import (
        ResilientRouter, RouterServer,
    )

    classes = tuple(c.strip() for c in args.priority_classes.split(",")
                    if c.strip())
    if not classes:
        raise SystemExit("--priority-classes must name at least one class")
    roles: tuple = ()
    if args.kv_roles:
        roles = tuple(r.strip() for r in args.kv_roles.split(",")
                      if r.strip())
        bad = sorted({r for r in roles
                      if r not in ("prefill", "decode", "mixed")})
        if bad:
            raise SystemExit(f"--kv-roles: unknown role(s) {bad} "
                             "(expected prefill|decode|mixed)")
        if (len(roles) >= args.replicas
                and all(r == "prefill" for r in roles[:args.replicas])):
            raise SystemExit("--kv-roles: every replica is 'prefill' — "
                             "at least one must be able to decode")
        if roles and not lm_specs:
            raise SystemExit("--kv-roles only applies to --lm servables")

    def _role(i: int) -> str:
        # replicas past the list (autoscaled growth included) serve mixed
        return roles[i] if i < len(roles) else "mixed"

    def _spec(i: int) -> ReplicaSpec:
        return ReplicaSpec(specs, buckets=buckets,
                           max_delay_ms=args.max_delay_ms,
                           queue_limit=args.queue_limit,
                           default_deadline_s=args.deadline_s,
                           enable_faults=args.enable_fault_injection,
                           lms=lm_specs, decode=decode_cfg,
                           trace_out=args.trace_out,
                           postmortem_dir=args.postmortem_dir,
                           flight=not args.no_flight,
                           flight_records=args.flight_records,
                           slo_availability=args.slo_availability,
                           slo_p99_ms=args.slo_p99_ms,
                           slo_sample_interval_s=args.slo_sample_interval_s,
                           slo_windows=args.slo_windows,
                           kv_role=_role(i))
    if args.replica_mode == "subprocess":
        for _, source in specs + lm_specs:
            base, _variant = parse_variant(source)
            if base.startswith("zoo:") or os.path.exists(base):
                continue
            raise SystemExit(f"fleet replicas cannot serve {source!r} "
                             "(need a path or zoo: name)")

        def factory(i):
            return SubprocessReplica(f"replica-{i}", _spec(i),
                                     env=dict(os.environ))
    else:
        def factory(i):
            return InProcessReplica(f"replica-{i}", _spec(i))

    autoscale = None
    if args.autoscale_max is not None:
        try:
            autoscale = AutoscaleConfig(
                min_replicas=args.replicas,
                max_replicas=args.autoscale_max,
                capacity_per_replica=args.per_replica_inflight,
                high_watermark=args.autoscale_high,
                low_watermark=args.autoscale_low,
                cooldown_s=args.autoscale_cooldown_s,
                drain_timeout_s=args.drain_timeout_s)
        except ValueError as e:
            raise SystemExit(f"--autoscale-*: {e}")
    supervisor = ReplicaSupervisor(
        factory, args.replicas,
        probe_interval_s=args.probe_interval_s,
        probe_timeout_s=args.probe_timeout_s,
        unhealthy_after=args.unhealthy_after,
        restart_budget=args.restart_budget,
        autoscale=autoscale)
    try:
        supervisor.start()
    except Exception as e:                    # noqa: BLE001
        raise SystemExit(f"fleet launch failed: {e}")
    router = ResilientRouter(
        supervisor.healthy, classes=classes,
        shed_floor=args.shed_floor,
        per_replica_inflight=args.per_replica_inflight,
        hedge=not args.no_hedge, timeout_s=args.deadline_s,
        slo_p99_ms=args.slo_p99_ms,
        canary_fraction=args.rollout_canary_fraction,
        affinity=not args.no_affinity,
        disagg_min_tokens=args.disagg_min_tokens,
        disagg_timeout_s=args.disagg_timeout_s)
    from deeplearning4j_tpu.monitor import slo as slo_mod
    slo_engine = _slo_setup(args, slo_mod.router_objectives(
        slo_p99_ms=args.slo_p99_ms,
        availability_target=args.slo_availability))
    server = RouterServer(router, supervisor=supervisor,
                          host=args.host, port=args.port,
                          slo_engine=slo_engine)
    rollout = None
    if args.rollout_watch is not None:
        from deeplearning4j_tpu.serving.rollout import RolloutController
        model_names = [n for n, _ in specs + lm_specs]
        rollout_model = args.rollout_model or (
            model_names[0] if model_names else None)
        if rollout_model is None:
            raise SystemExit("--rollout-watch needs a model "
                             "(--rollout-model or at least one --model)")
        rollout = RolloutController(
            supervisor, router, args.rollout_watch, rollout_model,
            watch=args.rollout_mode,
            poll_interval_s=args.rollout_poll_s,
            observe_s=args.rollout_observe_s,
            min_canary_requests=args.rollout_min_requests,
            p99_floor_ms=args.rollout_p99_floor_ms)
        server.rollout = rollout
        rollout.start()
    endpoints = ["/v1/models", "/v1/fleet", "/healthz", "/readyz",
                 "/metrics"]
    if slo_engine is not None:
        endpoints += ["/v1/slo", "/v1/timeseries"]
    print(json.dumps({"serving": server.url, "role": "router",
                      "replicas": [r.describe() for r in
                                   supervisor.replicas],
                      "priority_classes": list(classes),
                      "endpoints": endpoints,
                      "rollout": (rollout.describe()
                                  if rollout is not None else None),
                      "autoscale": (None if autoscale is None else
                                    {"min": autoscale.min_replicas,
                                     "max": autoscale.max_replicas})}))
    sys.stdout.flush()

    stop = threading.Event()

    def _on_signal(signum, frame):
        print(json.dumps({"signal": signum, "action": "fleet drain"}),
              file=sys.stderr)
        stop.set()

    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, _on_signal)
    stop.wait()
    # graceful fleet drain, same contract as single-replica mode: flip
    # /readyz to 503 FIRST so the balancer stops sending, give it a
    # moment to observe, let router-tracked in-flight work finish, and
    # only then tear the replicas down (their own SIGTERM drain flushes
    # whatever is still inside them)
    server.draining = True
    if rollout is not None:
        # settle the control loop first: a rollout mid-promotion must
        # not race the teardown's replica stops
        rollout.stop()
    grace = min(2.0, args.drain_timeout_s)
    time.sleep(grace)
    deadline = time.monotonic() + max(0.0, args.drain_timeout_s - grace)
    while time.monotonic() < deadline and any(
            r.inflight() for r in supervisor.replicas):
        time.sleep(0.1)
    supervisor.stop()
    server.stop()
    if args.trace_out:
        # supervisor.stop() SIGTERMed the replicas: each drained and
        # saved its own segment next to ours — trace_report merges them
        from deeplearning4j_tpu import monitor
        n = monitor.save_trace(args.trace_out)
        print(json.dumps({"trace_out": args.trace_out, "events": n,
                          "merge_hint": "tools/trace_report.py "
                                        f"{args.trace_out} "
                                        "<stem>.replica-*.json"}),
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
