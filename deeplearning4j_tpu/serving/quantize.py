"""Post-training quantization for decode servables.

Token-by-token decode is memory-bandwidth bound: every generated token
re-reads every weight once, so at batch sizes the decode slots actually
reach, the roofline (docs/OBSERVABILITY.md, PR 6) puts the step firmly
left of the ridge — tokens/sec is proportional to bytes moved, and weight
bytes dominate. Quantization is therefore the single biggest decode lever:

- **int8** — symmetric per-output-channel weight-only PTQ. Each weight
  matrix W is stored as ``int8 q`` plus a float32 per-channel ``scale``
  (``W ≈ q * scale``), computed over the contraction axis so each output
  channel keeps its own dynamic range (the standard LLM.int8()-family
  recipe for weight-only PTQ). Activations stay float; the dequantize is
  fused into the matmul by XLA. 4x smaller weight reads.
- **bf16** — a straight cast of params (and the KV cache, which the
  engine keys off the compute dtype): 2x smaller reads, near-zero quality
  cost, and the MXU-native dtype on TPU.

Quality is MEASURED, not assumed: `quality_delta()` scores base and
variant engines on the same token set (next-token perplexity + mean
absolute logit error) and `tools/decode_smoke.py` banks the numbers per
variant in DECODE_r*.json, where perf_report can see them next to the
tokens/sec they bought.

`QTensor` is a registered pytree so quantized params flow through jit
exactly like float params; `qdot`/`qtake` are the two consumption sites
(matmul and embedding lookup) the decode engine routes every quantizable
weight through.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

#: registry-variant names accepted as a ``@<mode>`` source suffix
QUANT_MODES = ("int8", "bf16")


@jax.tree_util.register_pytree_node_class
class QTensor:
    """Symmetric per-channel int8 weight: ``dequant = q * scale``.

    q: int8, original weight shape. scale: float32, shape broadcastable
    against q with the contraction (second-to-last) axis reduced — one
    scale per output channel (and per expert for stacked 3D weights)."""

    __slots__ = ("q", "scale")

    def __init__(self, q, scale):
        self.q = q
        self.scale = scale

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):            # reported dtype = storage dtype
        return self.q.dtype

    def tree_flatten(self):
        return (self.q, self.scale), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def dequant(self, dtype=jnp.float32):
        return self.q.astype(dtype) * self.scale.astype(dtype)

    def __repr__(self):
        return f"QTensor(int8 {tuple(self.q.shape)})"


def quantize_leaf(w) -> QTensor:
    """W (float, ndim >= 2) -> per-output-channel symmetric int8."""
    w = jnp.asarray(w)
    if w.ndim < 2:
        raise ValueError(f"quantize_leaf needs a matrix, got {w.shape}")
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127
                 ).astype(jnp.int8)
    return QTensor(q, scale.astype(jnp.float32))


def qdot(x, w):
    """x @ w where w is a float array or a QTensor (weight-only int8:
    the int8->float convert fuses into the matmul, so the weight is READ
    as int8 — the bandwidth win — and accumulated in float)."""
    if isinstance(w, QTensor):
        y = x @ w.q.astype(x.dtype)
        return y * w.scale.astype(x.dtype)
    return x @ w


def qtake(w, idx):
    """Embedding-row gather from a float array or QTensor table."""
    if isinstance(w, QTensor):
        rows = jnp.take(w.q, idx, axis=0).astype(jnp.float32)
        return rows * w.scale.astype(jnp.float32)
    return jnp.take(w, idx, axis=0)


def is_quantized(w) -> bool:
    return isinstance(w, QTensor)


def cast_tree_bf16(params):
    """bf16 servable variant: every float leaf -> bfloat16 (weights AND
    the activations/KV cache downstream, via the engine compute dtype)."""
    def cast(a):
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating):
            return a.astype(jnp.bfloat16)
        return a
    return jax.tree_util.tree_map(cast, params)


def is_spec_variant(variant) -> bool:
    """True for the speculative-decoding variant suffix: bare ``spec``
    or ``spec:opt=val,...`` (options parsed by decode.apply_variant)."""
    return isinstance(variant, str) and (
        variant == "spec" or variant.startswith("spec:"))


def parse_variant(source: str):
    """Split a servable source's ``@<variant>`` suffix: ``@int8`` /
    ``@bf16`` (quantized weights) or ``@spec[:...]`` (speculative
    decoding, serving/decode.py).

    ``zoo:TransformerLM?n_layers=2@int8`` -> (``zoo:...?n_layers=2``,
    ``"int8"``); ``ckpt@spec:draft=int8,k=4`` -> (``ckpt``,
    ``"spec:draft=int8,k=4"``); plain sources come back with variant
    None."""
    if isinstance(source, str) and "@" in source:
        # @spec splits at its FIRST occurrence: the options may name a
        # draft source carrying its own @int8/@bf16 suffix
        i = source.find("@spec")
        if i > 0 and is_spec_variant(source[i + 1:]):
            return source[:i], source[i + 1:]
        base, _, suffix = source.rpartition("@")
        if suffix in QUANT_MODES:
            return base, suffix
    return source, None


# --------------------------------------------------------------- quality
def _log_softmax(z: np.ndarray) -> np.ndarray:
    m = z.max(axis=-1, keepdims=True)
    s = z - m
    return s - np.log(np.exp(s).sum(axis=-1, keepdims=True))


def perplexity_from_logits(logits: np.ndarray, tokens: np.ndarray) -> float:
    """Next-token perplexity of (B, T, V) logits against (B, T) ids."""
    lp = _log_softmax(np.asarray(logits, np.float64))[:, :-1]
    tgt = np.asarray(tokens)[:, 1:].astype(int)
    nll = -np.take_along_axis(lp, tgt[..., None], axis=-1).mean()
    return float(np.exp(nll))


def quality_delta(base_engine, variant_engine, tokens) -> dict:
    """Measured accuracy cost of a quantized variant vs its base engine
    on one token batch: perplexity both ways, relative delta, and mean
    absolute logit error. This is the number decode_smoke banks per
    variant — quantization in this tree is never shipped unmeasured."""
    tokens = np.asarray(tokens, np.int32)
    base_logits = np.asarray(base_engine.logits_full(tokens), np.float32)
    var_logits = np.asarray(variant_engine.logits_full(tokens), np.float32)
    ppl_base = perplexity_from_logits(base_logits, tokens)
    ppl_var = perplexity_from_logits(var_logits, tokens)
    return {
        "ppl_base": round(ppl_base, 6),
        "ppl_variant": round(ppl_var, 6),
        "ppl_delta_pct": round(100.0 * (ppl_var - ppl_base)
                               / max(ppl_base, 1e-12), 4),
        "logit_mae": round(float(np.mean(np.abs(var_logits - base_logits))),
                           6),
    }


def quantize_params(params: dict, mode: Optional[str]):
    """Apply a variant mode to an extracted LM param tree.

    int8 quantizes exactly the leaves the decode engine consumes through
    qdot/qtake (attention projections, MLP matrices, the LM head, the
    embedding table); biases, layer norms and delegated per-token layers
    (MoE) stay float — they are bandwidth-irrelevant and some are consumed
    by stock layer.apply which expects plain arrays. bf16 casts the whole
    tree. None returns the tree untouched."""
    if mode is None:
        return params
    if mode == "bf16":
        return cast_tree_bf16(params)
    if mode != "int8":
        raise ValueError(f"unknown quantize mode {mode!r}; "
                         f"known: {QUANT_MODES}")

    def q2d(d, keys):
        for k in keys:
            if k in d:
                d[k] = quantize_leaf(d[k])

    out = jax.tree_util.tree_map(lambda a: a, params)   # shallow-ish copy
    for key, sub in out.items():
        if not isinstance(sub, dict):
            continue
        if "attn" in sub:                         # TransformerBlock
            q2d(sub["attn"], ("Wq", "Wk", "Wv", "Wo"))
            q2d(sub, ("W1", "W2"))
        elif set(sub) == {"W"} or set(sub) == {"W", "b"}:
            # embedding table or LM head projection
            q2d(sub, ("W",))
    return out
