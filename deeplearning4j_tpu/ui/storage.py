"""Stats storage — the pub/sub layer decoupling stats producers from UIs.

Parity: DL4J's storage abstraction
(`deeplearning4j-core/.../api/storage/StatsStorage.java` + `StatsStorageRouter`,
`Persistable`), with the two standard backends
(`deeplearning4j-ui-model/.../storage/InMemoryStatsStorage.java:20`,
`FileStatsStorage.java:15` — MapDB there, append-only JSONL here).

Records are keyed (session_id, type_id, worker_id, timestamp) exactly like
the reference's Persistable contract; static info and updates are separate
spaces (putStaticInfo vs putUpdate). Listeners receive StatsStorageEvent-
style callbacks (NewSessionID/NewTypeID/NewWorkerID/PostUpdate/PostStatic).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class StatsRecord:
    """One persistable record (DL4J api/storage/Persistable)."""
    session_id: str
    type_id: str
    worker_id: str
    timestamp: float
    data: Dict[str, Any]

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "StatsRecord":
        return StatsRecord(**json.loads(s))


class StatsStorageRouter:
    """Write-side API (DL4J StatsStorageRouter) — what listeners see."""

    def put_static_info(self, record: StatsRecord):
        raise NotImplementedError

    def put_update(self, record: StatsRecord):
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Readable storage + pub/sub (DL4J StatsStorage).

    Query API mirrors the reference: listSessionIDs,
    getAllUpdatesAfter, getLatestUpdate, getStaticInfo...
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._static: Dict[Tuple[str, str, str], StatsRecord] = {}
        self._updates: Dict[Tuple[str, str, str], List[StatsRecord]] = {}
        self._listeners: List[Callable[[str, StatsRecord], None]] = []

    # ------------------------------------------------------------- write
    def put_static_info(self, record: StatsRecord):
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            is_new_session = not any(
                k[0] == record.session_id
                for k in list(self._static) + list(self._updates))
            self._static[key] = record
            self._persist("static", record)
        if is_new_session:
            self._emit("new_session", record)
        self._emit("post_static", record)

    def put_update(self, record: StatsRecord):
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            is_new_session = not any(
                k[0] == record.session_id
                for k in list(self._static) + list(self._updates))
            self._updates.setdefault(key, []).append(record)
            self._persist("update", record)
        if is_new_session:
            self._emit("new_session", record)
        self._emit("post_update", record)

    # -------------------------------------------------------------- read
    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in
                           list(self._static) + list(self._updates)})

    def list_type_ids(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({k[1] for k in
                           list(self._static) + list(self._updates)
                           if k[0] == session_id})

    def list_worker_ids(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({k[2] for k in
                           list(self._static) + list(self._updates)
                           if k[0] == session_id})

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[StatsRecord]:
        with self._lock:
            return self._static.get((session_id, type_id, worker_id))

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str,
                              timestamp: float) -> List[StatsRecord]:
        with self._lock:
            recs = self._updates.get((session_id, type_id, worker_id), [])
            return [r for r in recs if r.timestamp > timestamp]

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[StatsRecord]:
        with self._lock:
            recs = self._updates.get((session_id, type_id, worker_id), [])
            return recs[-1] if recs else None

    def num_updates(self, session_id: str, type_id: str,
                    worker_id: str) -> int:
        with self._lock:
            return len(self._updates.get((session_id, type_id, worker_id), []))

    # ------------------------------------------------------------ pub/sub
    def register_stats_storage_listener(
            self, fn: Callable[[str, StatsRecord], None]):
        """fn(event, record); event in {new_session, post_static,
        post_update} (DL4J StatsStorageListener events)."""
        self._listeners.append(fn)

    def deregister_stats_storage_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def _emit(self, event: str, record: StatsRecord):
        for fn in list(self._listeners):
            try:
                fn(event, record)
            except Exception:       # listener errors never break training
                pass

    # --------------------------------------------------------- persistence
    def _persist(self, kind: str, record: StatsRecord):
        pass                        # in-memory backend: no-op

    def close(self):
        pass


class InMemoryStatsStorage(StatsStorage):
    """Pure in-memory backend (InMemoryStatsStorage.java:20)."""


class FileStatsStorage(StatsStorage):
    """File-backed storage: append-only JSONL, reloaded on open
    (FileStatsStorage.java:15 — MapDB there; JSONL keeps it dependency-free
    and makes records greppable)."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._file = None
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    entry = json.loads(line)
                    rec = StatsRecord(**entry["record"])
                    key = (rec.session_id, rec.type_id, rec.worker_id)
                    if entry["kind"] == "static":
                        self._static[key] = rec
                    else:
                        self._updates.setdefault(key, []).append(rec)
        self._file = open(path, "a")

    def _persist(self, kind: str, record: StatsRecord):
        if self._file is None:      # during __init__ replay
            return
        self._file.write(json.dumps(
            {"kind": kind, "record": dataclasses.asdict(record)},
            sort_keys=True) + "\n")
        self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


def new_session_id(prefix: str = "train") -> str:
    return f"{prefix}-{int(time.time() * 1000):x}-{os.getpid()}"


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """Streams StatsRecords to a remote UIServer over HTTP, so N worker
    processes/hosts feed ONE live dashboard.

    Parity: `api/storage/impl/RemoteUIStatsStorageRouter.java` (async
    queue + posting thread, exponential-backoff retries, shutdown after
    `max_retries` consecutive failures) posting to the receiver route the
    reference serves at POST /remoteReceive
    (`deeplearning4j-play/.../remote/RemoteReceiverModule.java:60`).
    Records batch per drain: one POST carries everything queued since the
    last one, so high-frequency listeners don't serialize on HTTP RTTs.

    Usage on a worker (any process/host that can reach the driver):
        router = RemoteUIStatsStorageRouter("http://driver:9000")
        net.set_listeners(StatsListener(router))
    and on the driver (bind 0.0.0.0 when workers live on OTHER hosts;
    the default loopback bind only serves same-host workers):
        UIServer(port=9000, host="0.0.0.0").enable_remote_listener()
    """

    def __init__(self, address: str, max_retries: int = 10,
                 retry_delay_ms: int = 1000,
                 retry_backoff_factor: float = 2.0,
                 path: str = "/remoteReceive"):
        import queue as _queue
        self.url = address.rstrip("/") + path
        self.max_retries = max_retries
        self.retry_delay = retry_delay_ms / 1000.0
        self.backoff = retry_backoff_factor
        self._q: "_queue.Queue" = _queue.Queue()
        self._shutdown = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="RemoteStatsRouter")
        self._thread.start()

    # -- StatsStorageRouter write API -----------------------------------
    def put_static_info(self, record: StatsRecord):
        self._enqueue("static", record)

    def put_update(self, record: StatsRecord):
        self._enqueue("update", record)

    def _enqueue(self, kind: str, record: StatsRecord):
        if self._shutdown.is_set():
            import logging
            logging.getLogger("deeplearning4j_tpu").warning(
                "RemoteUIStatsStorageRouter is shut down (too many "
                "consecutive post failures); dropping record")
            return
        self._idle.clear()
        self._q.put((kind, record))

    # -- posting thread ---------------------------------------------------
    def _drain(self):
        batch = []
        try:
            while True:
                batch.append(self._q.get_nowait())
        except Exception:
            pass
        return batch

    def _post(self, batch) -> bool:
        import urllib.request
        body = json.dumps({"records": [
            {"kind": kind, **dataclasses.asdict(rec)}
            for kind, rec in batch]}).encode()
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            return 200 <= resp.status < 300

    def _run(self):
        while not self._shutdown.is_set():
            if self._q.empty():
                self._idle.set()
                time.sleep(0.05)
                continue
            batch = self._drain()
            delay = self.retry_delay
            for attempt in range(self.max_retries + 1):
                try:
                    if self._post(batch):
                        break
                except Exception:
                    pass
                if attempt == self.max_retries:
                    # a batch that exhausted every retry shuts the router
                    # down, like the reference's repeated-failure shutdown —
                    # later records are dropped with a warning, training is
                    # never blocked on a dead dashboard
                    self._shutdown.set()
                    self._idle.set()
                    return
                # interruptible backoff: close() must not wait out the
                # exponential retry schedule
                if self._shutdown.wait(delay):
                    self._idle.set()
                    return
                delay *= self.backoff
        self._idle.set()

    # -- lifecycle --------------------------------------------------------
    def flush(self, timeout: float = 10.0) -> bool:
        """Block until everything queued so far has been posted (or the
        router shut down). Returns True if fully drained."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self._shutdown.is_set():
                return False
            if self._q.empty() and self._idle.is_set():
                return True
            time.sleep(0.02)
        return False

    def close(self):
        self.flush()
        self._shutdown.set()
