"""Stats storage — the pub/sub layer decoupling stats producers from UIs.

Parity: DL4J's storage abstraction
(`deeplearning4j-core/.../api/storage/StatsStorage.java` + `StatsStorageRouter`,
`Persistable`), with the two standard backends
(`deeplearning4j-ui-model/.../storage/InMemoryStatsStorage.java:20`,
`FileStatsStorage.java:15` — MapDB there, append-only JSONL here).

Records are keyed (session_id, type_id, worker_id, timestamp) exactly like
the reference's Persistable contract; static info and updates are separate
spaces (putStaticInfo vs putUpdate). Listeners receive StatsStorageEvent-
style callbacks (NewSessionID/NewTypeID/NewWorkerID/PostUpdate/PostStatic).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class StatsRecord:
    """One persistable record (DL4J api/storage/Persistable)."""
    session_id: str
    type_id: str
    worker_id: str
    timestamp: float
    data: Dict[str, Any]

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "StatsRecord":
        return StatsRecord(**json.loads(s))


class StatsStorageRouter:
    """Write-side API (DL4J StatsStorageRouter) — what listeners see."""

    def put_static_info(self, record: StatsRecord):
        raise NotImplementedError

    def put_update(self, record: StatsRecord):
        raise NotImplementedError


class StatsStorage(StatsStorageRouter):
    """Readable storage + pub/sub (DL4J StatsStorage).

    Query API mirrors the reference: listSessionIDs,
    getAllUpdatesAfter, getLatestUpdate, getStaticInfo...
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._static: Dict[Tuple[str, str, str], StatsRecord] = {}
        self._updates: Dict[Tuple[str, str, str], List[StatsRecord]] = {}
        self._listeners: List[Callable[[str, StatsRecord], None]] = []

    # ------------------------------------------------------------- write
    def put_static_info(self, record: StatsRecord):
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            is_new_session = not any(
                k[0] == record.session_id
                for k in list(self._static) + list(self._updates))
            self._static[key] = record
            self._persist("static", record)
        if is_new_session:
            self._emit("new_session", record)
        self._emit("post_static", record)

    def put_update(self, record: StatsRecord):
        key = (record.session_id, record.type_id, record.worker_id)
        with self._lock:
            is_new_session = not any(
                k[0] == record.session_id
                for k in list(self._static) + list(self._updates))
            self._updates.setdefault(key, []).append(record)
            self._persist("update", record)
        if is_new_session:
            self._emit("new_session", record)
        self._emit("post_update", record)

    # -------------------------------------------------------------- read
    def list_session_ids(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in
                           list(self._static) + list(self._updates)})

    def list_type_ids(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({k[1] for k in
                           list(self._static) + list(self._updates)
                           if k[0] == session_id})

    def list_worker_ids(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({k[2] for k in
                           list(self._static) + list(self._updates)
                           if k[0] == session_id})

    def get_static_info(self, session_id: str, type_id: str,
                        worker_id: str) -> Optional[StatsRecord]:
        with self._lock:
            return self._static.get((session_id, type_id, worker_id))

    def get_all_updates_after(self, session_id: str, type_id: str,
                              worker_id: str,
                              timestamp: float) -> List[StatsRecord]:
        with self._lock:
            recs = self._updates.get((session_id, type_id, worker_id), [])
            return [r for r in recs if r.timestamp > timestamp]

    def get_latest_update(self, session_id: str, type_id: str,
                          worker_id: str) -> Optional[StatsRecord]:
        with self._lock:
            recs = self._updates.get((session_id, type_id, worker_id), [])
            return recs[-1] if recs else None

    def num_updates(self, session_id: str, type_id: str,
                    worker_id: str) -> int:
        with self._lock:
            return len(self._updates.get((session_id, type_id, worker_id), []))

    # ------------------------------------------------------------ pub/sub
    def register_stats_storage_listener(
            self, fn: Callable[[str, StatsRecord], None]):
        """fn(event, record); event in {new_session, post_static,
        post_update} (DL4J StatsStorageListener events)."""
        self._listeners.append(fn)

    def deregister_stats_storage_listener(self, fn):
        if fn in self._listeners:
            self._listeners.remove(fn)

    def _emit(self, event: str, record: StatsRecord):
        for fn in list(self._listeners):
            try:
                fn(event, record)
            except Exception:       # listener errors never break training
                pass

    # --------------------------------------------------------- persistence
    def _persist(self, kind: str, record: StatsRecord):
        pass                        # in-memory backend: no-op

    def close(self):
        pass


class InMemoryStatsStorage(StatsStorage):
    """Pure in-memory backend (InMemoryStatsStorage.java:20)."""


class FileStatsStorage(StatsStorage):
    """File-backed storage: append-only JSONL, reloaded on open
    (FileStatsStorage.java:15 — MapDB there; JSONL keeps it dependency-free
    and makes records greppable)."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._file = None
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    entry = json.loads(line)
                    rec = StatsRecord(**entry["record"])
                    key = (rec.session_id, rec.type_id, rec.worker_id)
                    if entry["kind"] == "static":
                        self._static[key] = rec
                    else:
                        self._updates.setdefault(key, []).append(rec)
        self._file = open(path, "a")

    def _persist(self, kind: str, record: StatsRecord):
        if self._file is None:      # during __init__ replay
            return
        self._file.write(json.dumps(
            {"kind": kind, "record": dataclasses.asdict(record)},
            sort_keys=True) + "\n")
        self._file.flush()

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


def new_session_id(prefix: str = "train") -> str:
    return f"{prefix}-{int(time.time() * 1000):x}-{os.getpid()}"
