"""Reusable dashboard chart components (the `deeplearning4j-ui-components`
analog — the reference ships a TypeScript chart-component library under
deeplearning4j-ui-parent/deeplearning4j-ui-components/src/main/typescript/;
here it is one self-contained JS module, served at /assets/charts.js and
shared by every dashboard page, with zero external assets / egress).

Components:
    dl4j.line(svgEl|id, series, {names})   multi-series line chart
    dl4j.bars(svgEl|id, counts, lo, hi)    histogram bars
    dl4j.kvTable(el|id, rows)              key/value table
    dl4j.grid(el|id, header, rows)         generic table
    dl4j.palette                           series colors
"""

CHARTS_JS = r"""
const dl4j = (() => {
  const palette = ["#3366cc","#dc3912","#ff9900","#109618","#990099",
    "#0099c6","#dd4477","#66aa00","#b82e2e","#316395","#994499","#22aa99"];
  const el = x => typeof x === "string" ? document.getElementById(x) : x;
  const esc = s => String(s).replace(/&/g,'&amp;').replace(/</g,'&lt;')
                            .replace(/>/g,'&gt;');

  function line(target, series, opts) {
    const svg = el(target); svg.innerHTML = "";
    const names = (opts && opts.names) || null;
    const W = svg.width.baseVal.value, H = svg.height.baseVal.value, P = 36;
    let xs = [], ys = [];
    series.forEach(s => s.forEach(p => { xs.push(p[0]); ys.push(p[1]); }));
    if (!xs.length) return;
    const x0 = Math.min(...xs), x1 = Math.max(...xs);
    const y0 = Math.min(...ys), y1 = Math.max(...ys);
    const fx = v => P + (W-2*P) * (x1 > x0 ? (v-x0)/(x1-x0) : 0.5);
    const fy = v => H - P - (H-2*P) * (y1 > y0 ? (v-y0)/(y1-y0) : 0.5);
    let g = '';
    for (let i = 0; i <= 4; i++) {
      const y = y0 + (y1-y0)*i/4, py = fy(y);
      g += `<line x1="${P}" y1="${py}" x2="${W-P}" y2="${py}" stroke="#eee"/>`
         + `<text x="2" y="${py+4}" font-size="9">${y.toPrecision(3)}</text>`;
    }
    g += `<text x="${W/2}" y="${H-4}" font-size="9">`
       + `${x0.toFixed(0)} .. ${x1.toFixed(0)}</text>`;
    series.forEach((s, i) => {
      if (!s.length) return;
      const d = s.map((p, j) => (j ? 'L' : 'M')
        + fx(p[0]).toFixed(1) + ',' + fy(p[1]).toFixed(1)).join(' ');
      g += `<path d="${d}" fill="none" stroke="${palette[i%palette.length]}"`
         + ` stroke-width="1.5"/>`;
      if (names && names[i])
        g += `<text x="${W-P+2}" y="${16+12*i}" font-size="9"`
           + ` fill="${palette[i%palette.length]}">${esc(names[i])}</text>`;
    });
    svg.innerHTML = g;
  }

  function bars(target, counts, lo, hi) {
    const svg = el(target); svg.innerHTML = "";
    if (!counts || !counts.length) return;
    const W = svg.width.baseVal.value, H = svg.height.baseVal.value, P = 26;
    const m = Math.max(...counts, 1), bw = (W-2*P)/counts.length;
    let g = '';
    counts.forEach((c, i) => {
      const h = (H-2*P)*c/m;
      g += `<rect x="${P+i*bw}" y="${H-P-h}" width="${Math.max(bw-1,1)}"`
         + ` height="${h}" fill="#3366cc"/>`;
    });
    g += `<text x="${P}" y="${H-6}" font-size="9">`
       + `${lo !== undefined ? lo.toPrecision(3) : ''}</text>`;
    g += `<text x="${W-P-40}" y="${H-6}" font-size="9">`
       + `${hi !== undefined ? hi.toPrecision(3) : ''}</text>`;
    svg.innerHTML = g;
  }

  function scatter(target, points, opts) {
    // points: [[x, y, label?], ...]; one color per distinct label
    const svg = el(target); svg.innerHTML = "";
    if (!points || !points.length) return;
    const W = svg.width.baseVal.value, H = svg.height.baseVal.value, P = 30;
    const xs = points.map(p => p[0]), ys = points.map(p => p[1]);
    const x0 = Math.min(...xs), x1 = Math.max(...xs);
    const y0 = Math.min(...ys), y1 = Math.max(...ys);
    const fx = v => P + (W-2*P) * (x1 > x0 ? (v-x0)/(x1-x0) : 0.5);
    const fy = v => H - P - (H-2*P) * (y1 > y0 ? (v-y0)/(y1-y0) : 0.5);
    const labels = [...new Set(points.map(p => p[2]))];
    let g = '';
    points.forEach(p => {
      const c = palette[Math.max(labels.indexOf(p[2]), 0) % palette.length];
      g += `<circle cx="${fx(p[0]).toFixed(1)}" cy="${fy(p[1]).toFixed(1)}"`
         + ` r="2.5" fill="${c}" fill-opacity="0.7">`
         + `<title>${esc(p[2] !== undefined ? p[2] : '')}</title></circle>`;
    });
    labels.forEach((lb, i) => {
      if (lb === undefined) return;
      g += `<text x="${W-P+2}" y="${16+12*i}" font-size="9"`
         + ` fill="${palette[i%palette.length]}">${esc(lb)}</text>`;
    });
    svg.innerHTML = g;
  }

  async function applyI18n(lang) {
    const r = await fetch(`/i18n?lang=${encodeURIComponent(lang)}`);
    const cat = await r.json();
    document.querySelectorAll('[data-i18n]').forEach(n => {
      const t = cat[n.dataset.i18n];
      if (t) n.textContent = t;
    });
    document.querySelectorAll('[data-i18n-placeholder]').forEach(n => {
      const t = cat[n.dataset.i18nPlaceholder];
      if (t) n.placeholder = t;
    });
  }

  function kvTable(target, rows) {
    el(target).innerHTML = `<table><tr><th>field</th><th>value</th></tr>`
      + rows.map(([k, v]) =>
          `<tr><td>${esc(k)}</td><td>${esc(v)}</td></tr>`).join('')
      + `</table>`;
  }

  function grid(target, header, rows) {
    el(target).innerHTML = `<table><tr>`
      + header.map(h => `<th>${esc(h)}</th>`).join('') + `</tr>`
      + rows.map(r => `<tr>`
          + r.map(c => `<td>${esc(c)}</td>`).join('') + `</tr>`).join('')
      + `</table>`;
  }

  return { palette, line, bars, scatter, kvTable, grid, esc, applyI18n };
})();
"""

STYLE_CSS = """
 body{font-family:sans-serif;margin:0;background:#f4f6f8;color:#222}
 header{background:#223;color:#fff;padding:10px 16px;font-size:18px}
 header a{color:#9cf;text-decoration:none;margin-left:14px;font-size:13px}
 .row{display:flex;flex-wrap:wrap;gap:12px;padding:12px}
 .card{background:#fff;border-radius:6px;padding:10px 14px;
       box-shadow:0 1px 3px rgba(0,0,0,.15)}
 .card h3{margin:2px 0 8px 0;font-size:14px;color:#445}
 svg{background:#fafbfc;border:1px solid #e0e4e8}
 select{margin-left:12px}
 table{border-collapse:collapse;font-size:12px}
 td,th{border:1px solid #dde;padding:3px 8px;text-align:right}
 th{background:#eef}
 td:first-child,th:first-child{text-align:left}
"""
