"""UIServer — the training dashboard.

Parity: DL4J `deeplearning4j-play/.../play/PlayUIServer.java` +
`module/train/TrainModule.java` (overview / model / system tabs fed by an
attached StatsStorage, live-updating browser charts).

TPU-native redesign: stdlib ThreadingHTTPServer serving ONE self-contained
HTML page (inline JS+SVG, no external assets — zero egress) that polls JSON
endpoints. Endpoints mirror TrainModule's routes:
    /train/sessions            -> session ids
    /train/data?sid=&after=    -> static info + updates since a timestamp
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.ui.storage import StatsStorage

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>DL4J-TPU Training UI</title>
<style>
 body{font-family:sans-serif;margin:0;background:#f4f6f8;color:#222}
 header{background:#223;color:#fff;padding:10px 16px;font-size:18px}
 .row{display:flex;flex-wrap:wrap;gap:12px;padding:12px}
 .card{background:#fff;border-radius:6px;padding:10px 14px;
       box-shadow:0 1px 3px rgba(0,0,0,.15)}
 .card h3{margin:2px 0 8px 0;font-size:14px;color:#445}
 svg{background:#fafbfc;border:1px solid #e0e4e8}
 select{margin-left:12px}
 table{border-collapse:collapse;font-size:12px}
 td,th{border:1px solid #dde;padding:3px 8px;text-align:right}
 th{background:#eef}
 td:first-child,th:first-child{text-align:left}
</style></head><body>
<header>DL4J-TPU Training Dashboard
 <select id="sess"></select>
 <span id="status" style="font-size:12px;margin-left:12px"></span>
</header>
<div class="row">
 <div class="card"><h3>Score vs iteration</h3><svg id="score" width="460" height="220"></svg></div>
 <div class="card"><h3>Samples/sec</h3><svg id="perf" width="460" height="220"></svg></div>
 <div class="card"><h3>Device memory (MB in use)</h3><svg id="mem" width="460" height="220"></svg></div>
</div>
<div class="row">
 <div class="card"><h3>Parameter mean magnitudes (log10)</h3><svg id="pmag" width="700" height="240"></svg></div>
 <div class="card"><h3>Update:param ratio (log10, healthy ~ -3)</h3><svg id="ratio" width="700" height="240"></svg></div>
</div>
<div class="row">
 <div class="card"><h3>Model / session info</h3><div id="info" style="font-size:12px"></div></div>
 <div class="card"><h3>Last gradient histogram <select id="hsel"></select></h3>
  <svg id="hist" width="460" height="220"></svg></div>
</div>
<script>
let updates=[], statics={}, after=0, sid=null, histKey=null;
const colors=["#3366cc","#dc3912","#ff9900","#109618","#990099","#0099c6",
  "#dd4477","#66aa00","#b82e2e","#316395","#994499","#22aa99"];
function line(svgId, series, names){
  const svg=document.getElementById(svgId); svg.innerHTML="";
  const W=svg.width.baseVal.value,H=svg.height.baseVal.value,P=36;
  let xs=[],ys=[];
  series.forEach(s=>s.forEach(p=>{xs.push(p[0]);ys.push(p[1]);}));
  if(!xs.length)return;
  const x0=Math.min(...xs),x1=Math.max(...xs),y0=Math.min(...ys),y1=Math.max(...ys);
  const fx=v=>P+(W-2*P)*(x1>x0?(v-x0)/(x1-x0):0.5);
  const fy=v=>H-P-(H-2*P)*(y1>y0?(v-y0)/(y1-y0):0.5);
  let g='';
  for(let i=0;i<=4;i++){const y=y0+(y1-y0)*i/4, py=fy(y);
    g+=`<line x1="${P}" y1="${py}" x2="${W-P}" y2="${py}" stroke="#eee"/>`+
       `<text x="2" y="${py+4}" font-size="9">${y.toPrecision(3)}</text>`;}
  g+=`<text x="${W/2}" y="${H-4}" font-size="9">${x0.toFixed(0)} .. ${x1.toFixed(0)}</text>`;
  series.forEach((s,i)=>{
    if(!s.length)return;
    const d=s.map((p,j)=>(j?'L':'M')+fx(p[0]).toFixed(1)+','+fy(p[1]).toFixed(1)).join(' ');
    g+=`<path d="${d}" fill="none" stroke="${colors[i%colors.length]}" stroke-width="1.5"/>`;
    if(names&&names[i])g+=`<text x="${W-P+2}" y="${16+12*i}" font-size="9" fill="${colors[i%colors.length]}">${names[i]}</text>`;
  });
  svg.innerHTML=g;
}
function bars(svgId, counts, lo, hi){
  const svg=document.getElementById(svgId); svg.innerHTML="";
  if(!counts||!counts.length)return;
  const W=svg.width.baseVal.value,H=svg.height.baseVal.value,P=26;
  const m=Math.max(...counts,1),bw=(W-2*P)/counts.length;
  let g='';
  counts.forEach((c,i)=>{const h=(H-2*P)*c/m;
    g+=`<rect x="${P+i*bw}" y="${H-P-h}" width="${Math.max(bw-1,1)}" height="${h}" fill="#3366cc"/>`;});
  g+=`<text x="${P}" y="${H-6}" font-size="9">${lo!==undefined?lo.toPrecision(3):''}</text>`;
  g+=`<text x="${W-P-40}" y="${H-6}" font-size="9">${hi!==undefined?hi.toPrecision(3):''}</text>`;
  svg.innerHTML=g;
}
async function refreshSessions(){
  const r=await fetch('train/sessions'); const j=await r.json();
  const sel=document.getElementById('sess');
  const cur=sel.value;
  sel.innerHTML=j.sessions.map(s=>`<option>${s}</option>`).join('');
  if(j.sessions.includes(cur))sel.value=cur;
  if(!sid&&j.sessions.length){sid=sel.value;}
}
async function poll(){
  try{
    await refreshSessions();
    const sel=document.getElementById('sess');
    if(sel.value&&sel.value!==sid){sid=sel.value;updates=[];after=0;}
    if(!sid){setTimeout(poll,2000);return;}
    const r=await fetch(`train/data?sid=${encodeURIComponent(sid)}&after=${after}`);
    const j=await r.json();
    statics=j.static||{};
    if(j.updates.length){
      updates=updates.concat(j.updates);
      after=j.updates[j.updates.length-1].timestamp;
      if(updates.length>2000)updates=updates.slice(-2000);
    }
    render();
    document.getElementById('status').textContent=
      `${updates.length} records | live`;
  }catch(e){document.getElementById('status').textContent='disconnected';}
  setTimeout(poll,2000);
}
function render(){
  const d=updates.map(u=>u.data);
  line('score',[d.map(u=>[u.iteration,u.score])]);
  line('perf',[d.filter(u=>u.samples_sec>0).map(u=>[u.iteration,u.samples_sec])]);
  line('mem',[d.filter(u=>u.memory&&u.memory.device_bytes_in_use)
     .map(u=>[u.iteration,u.memory.device_bytes_in_use/1048576])]);
  const last=d[d.length-1]; if(!last)return;
  const keys=Object.keys(last.params||{});
  line('pmag',keys.map(k=>d.filter(u=>u.params&&u.params[k])
     .map(u=>[u.iteration,Math.log10(u.params[k].mean_mag+1e-12)])),keys);
  line('ratio',keys.map(k=>d.filter(u=>u.updates&&u.updates[k]&&u.params[k])
     .map(u=>[u.iteration,Math.log10((u.updates[k].mean_mag+1e-12)/(u.params[k].mean_mag+1e-12))])),keys);
  const hsel=document.getElementById('hsel');
  const gkeys=Object.keys(last.gradients||{});
  if(hsel.options.length!==gkeys.length){
    hsel.innerHTML=gkeys.map(k=>`<option>${k}</option>`).join('');}
  histKey=hsel.value||gkeys[0];
  if(histKey&&last.gradients&&last.gradients[histKey]){
    const h=last.gradients[histKey];
    bars('hist',h.hist,h.lo,h.hi);}
  const si=statics.data||{};
  document.getElementById('info').innerHTML=
    `<table><tr><th>field</th><th>value</th></tr>`+
    ['model_class','num_params','num_layers','devices'].map(k=>
      `<tr><td>${k}</td><td>${JSON.stringify(si[k])}</td></tr>`).join('')+
    `<tr><td>score (last)</td><td>${last.score.toPrecision(5)}</td></tr>`+
    `<tr><td>iteration</td><td>${last.iteration}</td></tr></table>`+
    (si.summary?`<pre style="font-size:11px">${String(si.summary)
      .replace(/&/g,'&amp;').replace(/</g,'&lt;')
      .replace(/>/g,'&gt;')}</pre>`:'');
}
poll();
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "DL4JTPU-UI/1.0"

    def log_message(self, *a):       # silence request logging
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        ui: "UIServer" = self.server.ui           # type: ignore[attr-defined]
        url = urlparse(self.path)
        if url.path in ("/", "/train", "/train/overview"):
            body = _PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if url.path == "/train/sessions":
            self._json({"sessions": ui.session_ids()})
            return
        if url.path == "/train/data":
            q = parse_qs(url.query)
            sid = q.get("sid", [""])[0]
            after = float(q.get("after", ["0"])[0])
            self._json(ui.session_data(sid, after))
            return
        self._json({"error": "not found"}, code=404)


class UIServer:
    """Singleton dashboard server (PlayUIServer.getInstance() parity).

    Usage:
        server = UIServer.get_instance()     # starts on a free port
        server.attach(storage)
        print(server.url)
    """

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 0):
        self._storages: list = []
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.ui = self                    # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="UIServer")
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/"

    def attach(self, storage: StatsStorage):
        """Attach a stats storage to visualize (UIServer.attach parity)."""
        if storage not in self._storages:
            self._storages.append(storage)

    def detach(self, storage: StatsStorage):
        if storage in self._storages:
            self._storages.remove(storage)

    # ----------------------------------------------------------- queries
    def session_ids(self):
        out = []
        for s in self._storages:
            out.extend(s.list_session_ids())
        return sorted(set(out))

    def session_data(self, sid: str, after: float) -> Dict:
        static = None
        updates = []
        for s in self._storages:
            for tid in s.list_type_ids(sid):
                for wid in s.list_worker_ids(sid):
                    st = s.get_static_info(sid, tid, wid)
                    if st is not None and static is None:
                        static = {"timestamp": st.timestamp, "data": st.data}
                    for r in s.get_all_updates_after(sid, tid, wid, after):
                        updates.append({"timestamp": r.timestamp,
                                        "data": r.data})
        updates.sort(key=lambda r: r["timestamp"])
        return {"static": static, "updates": updates[:500]}

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None
