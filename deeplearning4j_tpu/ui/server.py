"""UIServer — the training dashboard.

Parity: DL4J `deeplearning4j-play/.../play/PlayUIServer.java` +
`module/train/TrainModule.java` (overview / model / system tabs fed by an
attached StatsStorage, live-updating browser charts).

TPU-native redesign: stdlib ThreadingHTTPServer serving self-contained
HTML pages (SVG charts drawn by the shared /assets/charts.js component
module — the `deeplearning4j-ui-components` analog, see ui/components.py —
no external assets, zero egress) that poll JSON endpoints. Endpoints
mirror TrainModule's routes:
    /train            (overview tab: score, throughput, memory, ratios)
    /train/model      (model tab: per-layer drill-down)
    /train/sessions            -> session ids
    /train/data?sid=&after=    -> static info + updates since a timestamp
    /assets/charts.js          -> reusable chart components
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, unquote, urlparse

from deeplearning4j_tpu.ui.components import CHARTS_JS, STYLE_CSS
from deeplearning4j_tpu.ui.storage import StatsStorage

_HEAD = f"""<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>DL4J-TPU Training UI</title>
<style>{STYLE_CSS}</style>
<script src="/assets/charts.js"></script>
</head><body>
<header>DL4J-TPU Training Dashboard
 <a href="/train" data-i18n="train.nav.overview">overview</a><a
  href="/train/model" data-i18n="train.nav.model">model</a><a
  href="/train/system" data-i18n="train.nav.system">system</a><a
  href="/tsne" data-i18n="train.nav.tsne">t-SNE</a><a
  href="/word2vec" data-i18n="train.nav.word2vec">word2vec</a>
 <select id="sess"></select>
 <select id="lang" onchange="dl4j.applyI18n(this.value)">
  <option>en</option><option>de</option><option>ja</option>
  <option>ko</option><option>ru</option><option>zh</option></select>
 <span id="status" style="font-size:12px;margin-left:12px"></span>
</header>
<script>
let updates=[], statics={{}}, after=0, sid=null;
async function refreshSessions(){{
  const r=await fetch('/train/sessions'); const j=await r.json();
  const sel=document.getElementById('sess');
  const cur=sel.value;
  sel.innerHTML=j.sessions.map(s=>`<option>${{dl4j.esc(s)}}</option>`).join('');
  if(j.sessions.includes(cur))sel.value=cur;
  if(!sid&&j.sessions.length){{sid=sel.value;}}
}}
async function poll(){{
  try{{
    await refreshSessions();
    const sel=document.getElementById('sess');
    if(sel.value&&sel.value!==sid){{sid=sel.value;updates=[];after=0;}}
    if(!sid){{setTimeout(poll,2000);return;}}
    const r=await fetch(`/train/data?sid=${{encodeURIComponent(sid)}}&after=${{after}}`);
    const j=await r.json();
    statics=j.static||{{}};
    if(j.updates.length){{
      updates=updates.concat(j.updates);
      after=j.updates[j.updates.length-1].timestamp;
      if(updates.length>2000)updates=updates.slice(-2000);
    }}
    render();
    document.getElementById('status').textContent=
      `${{updates.length}} records | live`;
  }}catch(e){{document.getElementById('status').textContent='disconnected';}}
  setTimeout(poll,2000);
}}
</script>
"""

_OVERVIEW_PAGE = _HEAD + """
<div class="row">
 <div class="card"><h3 data-i18n="train.overview.chart.score">Score vs iteration</h3><svg id="score" width="460" height="220"></svg></div>
 <div class="card"><h3 data-i18n="train.overview.chart.throughput">Samples/sec</h3><svg id="perf" width="460" height="220"></svg></div>
 <div class="card"><h3 data-i18n="train.overview.chart.memory">Device memory (MB in use)</h3><svg id="mem" width="460" height="220"></svg></div>
</div>
<div class="row">
 <div class="card"><h3 data-i18n="train.overview.chart.paramMag">Parameter mean magnitudes (log10)</h3><svg id="pmag" width="700" height="240"></svg></div>
 <div class="card"><h3 data-i18n="train.overview.chart.ratio">Update:param ratio (log10, healthy ~ -3)</h3><svg id="ratio" width="700" height="240"></svg></div>
</div>
<div class="row">
 <div class="card"><h3 data-i18n="train.overview.info">Model / session info</h3><div id="info" style="font-size:12px"></div></div>
 <div class="card"><h3><span data-i18n="train.overview.chart.gradHist">Last gradient histogram</span> <select id="hsel"></select></h3>
  <svg id="hist" width="460" height="220"></svg></div>
</div>
<script>
function render(){
  const d=updates.map(u=>u.data);
  dl4j.line('score',[d.map(u=>[u.iteration,u.score])]);
  dl4j.line('perf',[d.filter(u=>u.samples_sec>0).map(u=>[u.iteration,u.samples_sec])]);
  dl4j.line('mem',[d.filter(u=>u.memory&&u.memory.device_bytes_in_use)
     .map(u=>[u.iteration,u.memory.device_bytes_in_use/1048576])]);
  const last=d[d.length-1]; if(!last)return;
  const keys=Object.keys(last.params||{});
  dl4j.line('pmag',keys.map(k=>d.filter(u=>u.params&&u.params[k])
     .map(u=>[u.iteration,Math.log10(u.params[k].mean_mag+1e-12)])),{names:keys});
  dl4j.line('ratio',keys.map(k=>d.filter(u=>u.updates&&u.updates[k]&&u.params[k])
     .map(u=>[u.iteration,Math.log10((u.updates[k].mean_mag+1e-12)/(u.params[k].mean_mag+1e-12))])),{names:keys});
  const hsel=document.getElementById('hsel');
  const gkeys=Object.keys(last.gradients||{});
  if(hsel.options.length!==gkeys.length){
    hsel.innerHTML=gkeys.map(k=>`<option>${dl4j.esc(k)}</option>`).join('');}
  const histKey=hsel.value||gkeys[0];
  if(histKey&&last.gradients&&last.gradients[histKey]){
    const h=last.gradients[histKey];
    dl4j.bars('hist',h.hist,h.lo,h.hi);}
  const si=statics.data||{};
  dl4j.kvTable('info',
    ['model_class','num_params','num_layers','devices'].map(k=>
      [k,JSON.stringify(si[k])])
    .concat([['score (last)',last.score.toPrecision(5)],
             ['iteration',last.iteration]]));
  if(si.summary)document.getElementById('info').innerHTML+=
    `<pre style="font-size:11px">${dl4j.esc(si.summary)}</pre>`;
}
poll();
</script></body></html>
"""

_MODEL_PAGE = _HEAD + """
<div class="row">
 <div class="card" style="min-width:280px"><h3 data-i18n="train.model.layers">Layers (click to select)</h3>
  <div id="ltable" style="font-size:12px"></div></div>
 <div class="card"><h3 id="ltitle">Layer</h3><div id="ldetail" style="font-size:12px"></div></div>
</div>
<div class="row">
 <div class="card"><h3 data-i18n="train.model.paramMag">Mean magnitude: parameters (log10)</h3><svg id="lpmag" width="460" height="220"></svg></div>
 <div class="card"><h3 data-i18n="train.model.gradMag">Mean magnitude: gradients (log10)</h3><svg id="lgmag" width="460" height="220"></svg></div>
 <div class="card"><h3>Update:param ratio (log10)</h3><svg id="lratio" width="460" height="220"></svg></div>
</div>
<div class="row">
 <div class="card"><h3>Parameter histogram <select id="lpsel"></select></h3>
  <svg id="lphist" width="460" height="220"></svg></div>
 <div class="card"><h3>Gradient histogram</h3><svg id="lghist" width="460" height="220"></svg></div>
 <div class="card"><h3>Update histogram</h3><svg id="luhist" width="460" height="220"></svg></div>
</div>
<script>
let layer=null;
function layerKeys(rec, group, name){
  return Object.keys(rec[group]||{}).filter(k=>k.split('/')[0]===name);
}
function selectLayer(name){ layer=name; render(); }
function render(){
  const si=statics.data||{};
  const layers=si.layers||[];
  if(layer===null&&layers.length)layer=layers[0].name;
  dl4j.grid('ltable',['layer','type','n_params'],
    layers.map(l=>[l.name,l.type,l.n_params]));
  // row click-through: rebuild with onclick handles
  const rows=document.querySelectorAll('#ltable tr');
  layers.forEach((l,i)=>{
    const tr=rows[i+1]; if(!tr)return;
    tr.style.cursor='pointer';
    if(l.name===layer)tr.style.background='#dde8ff';
    tr.onclick=()=>selectLayer(l.name);
  });
  const d=updates.map(u=>u.data);
  const last=d[d.length-1];
  if(!last||layer===null)return;
  const info=layers.find(l=>l.name===layer)||{};
  document.getElementById('ltitle').textContent=
    `Layer ${layer} (${info.type||'?'})`;
  dl4j.kvTable('ldetail',
    [['type',info.type],['n_params',info.n_params]].concat(
      Object.entries(info.shapes||{}).map(([k,v])=>
        ['shape '+k,JSON.stringify(v)])));
  const pkeys=layerKeys(last,'params',layer);
  dl4j.line('lpmag',pkeys.map(k=>d.filter(u=>u.params&&u.params[k])
    .map(u=>[u.iteration,Math.log10(u.params[k].mean_mag+1e-12)])),{names:pkeys});
  const gkeys=layerKeys(last,'gradients',layer);
  dl4j.line('lgmag',gkeys.map(k=>d.filter(u=>u.gradients&&u.gradients[k])
    .map(u=>[u.iteration,Math.log10(u.gradients[k].mean_mag+1e-12)])),{names:gkeys});
  dl4j.line('lratio',pkeys.map(k=>d.filter(u=>u.updates&&u.updates[k]&&u.params[k])
    .map(u=>[u.iteration,Math.log10((u.updates[k].mean_mag+1e-12)/(u.params[k].mean_mag+1e-12))])),{names:pkeys});
  const sel=document.getElementById('lpsel');
  if(sel.dataset.keys!==pkeys.join()){   // layer switch: rebuild options
    sel.innerHTML=pkeys.map(k=>`<option>${dl4j.esc(k)}</option>`).join('');
    sel.dataset.keys=pkeys.join();
  }
  const pk=sel.value||pkeys[0];
  if(pk&&last.params[pk]&&last.params[pk].hist)
    dl4j.bars('lphist',last.params[pk].hist,last.params[pk].lo,last.params[pk].hi);
  const gk=(layerKeys(last,'gradients',layer))[Math.max(0,sel.selectedIndex)];
  if(gk&&last.gradients[gk]&&last.gradients[gk].hist)
    dl4j.bars('lghist',last.gradients[gk].hist,last.gradients[gk].lo,last.gradients[gk].hi);
  const uk=(layerKeys(last,'updates',layer))[Math.max(0,sel.selectedIndex)];
  if(uk&&last.updates[uk]&&last.updates[uk].hist)
    dl4j.bars('luhist',last.updates[uk].hist,last.updates[uk].lo,last.updates[uk].hi);
}
poll();
</script></body></html>
"""


_SYSTEM_PAGE = _HEAD + """
<div class="row">
 <div class="card"><h3>Devices</h3><div id="devs" style="font-size:12px"></div></div>
 <div class="card"><h3>Host memory (max RSS, MB)</h3><svg id="rss" width="460" height="220"></svg></div>
 <div class="card"><h3>Device memory (MB in use)</h3><svg id="dmem" width="460" height="220"></svg></div>
</div>
<div class="row">
 <div class="card"><h3>Iteration time (ms)</h3><svg id="itms" width="460" height="220"></svg></div>
 <div class="card"><h3>ETL time (ms)</h3><svg id="etl" width="460" height="220"></svg></div>
</div>
<script>
function render(){
  const d=updates.map(u=>u.data);
  const si=statics.data||{};
  dl4j.kvTable('devs',[['devices',JSON.stringify(si.devices)],
    ['model_class',si.model_class],['num_params',si.num_params]]);
  dl4j.line('rss',[d.filter(u=>u.memory&&u.memory.host_max_rss_kb)
    .map(u=>[u.iteration,u.memory.host_max_rss_kb/1024])]);
  dl4j.line('dmem',[d.filter(u=>u.memory&&u.memory.device_bytes_in_use)
    .map(u=>[u.iteration,u.memory.device_bytes_in_use/1048576])]);
  dl4j.line('itms',[d.filter(u=>u.iter_ms>0).map(u=>[u.iteration,u.iter_ms])]);
  dl4j.line('etl',[d.map(u=>[u.iteration,u.etl_ms])]);
}
poll();
</script></body></html>
"""

_TSNE_PAGE = _HEAD + """
<div class="row">
 <div class="card"><h3 data-i18n="tsne.title">t-SNE embedding</h3>
  <select id="tsess"></select>
  <svg id="plot" width="760" height="560"></svg></div>
</div>
<script>
function render(){}
async function tsnePoll(){
  try{
    const r=await fetch('/tsne/sessions'); const j=await r.json();
    const sel=document.getElementById('tsess');
    const cur=sel.value;
    sel.innerHTML=j.sessions.map(s=>`<option>${dl4j.esc(s)}</option>`).join('');
    if(j.sessions.includes(cur))sel.value=cur;
    if(sel.value){
      const c=await fetch(`/tsne/coords/${encodeURIComponent(sel.value)}`);
      const d=await c.json();
      dl4j.scatter('plot', d.points);
      document.getElementById('status').textContent=
        `${d.points.length} points`;
    }
  }catch(e){document.getElementById('status').textContent='disconnected';}
  setTimeout(tsnePoll,3000);
}
tsnePoll();
</script></body></html>
"""

_W2V_PAGE = _HEAD + """
<div class="row">
 <div class="card"><h3 data-i18n="word2vec.title">Nearest words</h3>
  <input id="word" data-i18n-placeholder="word2vec.prompt" placeholder="word">
  <input id="topn" type="number" value="10" style="width:52px">
  <button onclick="query()">&rarr;</button>
  <div id="result" style="font-size:13px;margin-top:10px"></div></div>
</div>
<script>
function render(){}
async function query(){
  const w=document.getElementById('word').value;
  const n=document.getElementById('topn').value;
  const r=await fetch(`/word2vec/nearest?word=${encodeURIComponent(w)}&n=${n}`);
  const j=await r.json();
  if(j.error){document.getElementById('result').textContent=j.error;return;}
  dl4j.grid('result',['word','similarity'],
    j.nearest.map(e=>[e.word,e.similarity.toFixed(4)]));
}
document.getElementById('word').addEventListener('keydown',
  e=>{if(e.key==='Enter')query();});
</script></body></html>
"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "DL4JTPU-UI/1.0"

    def log_message(self, *a):       # silence request logging
        pass

    def _json(self, obj, code=200):
        self._raw(json.dumps(obj).encode(), "application/json", code)

    def _raw(self, body: bytes, ctype: str, code: int = 200):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        ui: "UIServer" = self.server.ui           # type: ignore[attr-defined]
        url = urlparse(self.path)
        if url.path in ("/", "/train", "/train/overview"):
            self._raw(_OVERVIEW_PAGE.encode(), "text/html; charset=utf-8")
            return
        if url.path == "/train/model":
            self._raw(_MODEL_PAGE.encode(), "text/html; charset=utf-8")
            return
        if url.path == "/train/system":
            self._raw(_SYSTEM_PAGE.encode(), "text/html; charset=utf-8")
            return
        if url.path == "/assets/charts.js":
            self._raw(CHARTS_JS.encode(),
                      "application/javascript; charset=utf-8")
            return
        if url.path == "/tsne":
            self._raw(_TSNE_PAGE.encode(), "text/html; charset=utf-8")
            return
        if url.path == "/word2vec":
            self._raw(_W2V_PAGE.encode(), "text/html; charset=utf-8")
            return
        if url.path == "/i18n":
            from deeplearning4j_tpu.ui.i18n import catalog
            lang = parse_qs(url.query).get("lang", ["en"])[0]
            self._json(catalog(lang))
            return
        if url.path == "/tsne/sessions":
            with ui._tsne_lock:
                sessions = sorted(ui._tsne_sessions)
            self._json({"sessions": sessions})
            return
        if url.path.startswith("/tsne/coords/"):
            sid = unquote(url.path.rsplit("/", 1)[-1])
            with ui._tsne_lock:
                pts = ui._tsne_sessions.get(sid)
            if pts is None:
                self._json({"error": f"unknown t-SNE session '{sid}'"},
                           code=404)
            else:
                self._json({"points": pts})
            return
        if url.path == "/word2vec/nearest":
            q = parse_qs(url.query)
            word = q.get("word", [""])[0]
            try:
                n = max(1, int(q.get("n", ["10"])[0]))
            except ValueError:
                n = 10
            self._json(ui.nearest_words(word, n))
            return
        if url.path == "/train/sessions":
            self._json({"sessions": ui.session_ids()})
            return
        if url.path == "/metrics":
            # Prometheus text exposition of the process-global telemetry
            # registry (monitor/metrics.py) — scrape target for ops
            from deeplearning4j_tpu.monitor import prometheus_text
            self._raw(prometheus_text().encode(),
                      "text/plain; version=0.0.4; charset=utf-8")
            return
        if url.path == "/train/data":
            q = parse_qs(url.query)
            sid = q.get("sid", [""])[0]
            try:
                after = float(q.get("after", ["0"])[0])
            except ValueError:
                self._json({"error": "bad 'after' parameter: not a number"},
                           code=400)
                return
            if sid not in ui.session_ids():
                self._json({"error": f"unknown session id '{sid}'"},
                           code=404)
                return
            self._json(ui.session_data(sid, after))
            return
        self._json({"error": "not found"}, code=404)

    def _post_body(self):
        """Read and json-parse the POST body; raises ValueError on a
        bad/abusive Content-Length or non-JSON payload (the caller maps
        that to a clean 400, never a 500 traceback)."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except (TypeError, ValueError):
            raise ValueError("bad Content-Length header")
        if length < 0 or length > (64 << 20):
            raise ValueError(f"unreasonable Content-Length {length}")
        return json.loads(self.rfile.read(length) or b"{}")

    def do_POST(self):
        # TsneModule.java route parity: POST /tsne/post/<sid> with a JSON
        # body {"points": [[x, y, label?], ...]}
        ui: "UIServer" = self.server.ui           # type: ignore[attr-defined]
        url = urlparse(self.path)
        if url.path == "/remoteReceive":
            # RemoteReceiverModule.java:60 parity: workers' remote stats
            # routers POST record batches here; they land in the storage
            # registered via UIServer.enable_remote_listener()
            # AttributeError covers a well-formed-JSON body that is not an
            # object (e.g. a bare list: .get would 500 with a traceback)
            try:
                body = self._post_body()
                n = ui.receive_remote(body.get("records", []))
            except (ValueError, KeyError, TypeError, AttributeError) as e:
                self._json({"error": f"bad body: {e}"}, code=400)
                return
            if n is None:
                self._json({"error": "remote listener not enabled"},
                           code=409)
                return
            self._json({"ok": True, "n": n})
            return
        if url.path.startswith("/tsne/post/"):
            sid = unquote(url.path.rsplit("/", 1)[-1])
            try:
                body = self._post_body()
                pts = body["points"]
                ui.post_tsne(sid, pts)
            except (ValueError, KeyError, TypeError, IndexError,
                    AttributeError) as e:
                self._json({"error": f"bad body: {e}"}, code=400)
                return
            self._json({"ok": True, "n": len(pts)})
            return
        self._json({"error": "not found"}, code=404)


class UIServer:
    """Singleton dashboard server (PlayUIServer.getInstance() parity).

    Usage:
        server = UIServer.get_instance()     # starts on a free port
        server.attach(storage)
        print(server.url)
    """

    _instance: Optional["UIServer"] = None

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        """`host` defaults to loopback; a driver accepting remote worker
        stats from OTHER hosts (enable_remote_listener + workers using
        RemoteUIStatsStorageRouter) must bind host="0.0.0.0" like the
        reference's Play server does."""
        self._storages: list = []
        self._tsne_sessions: Dict[str, list] = {}
        self._tsne_lock = threading.Lock()
        self._word_vectors = None
        self._remote_storage = None
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.ui = self                    # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="UIServer")
        self._thread.start()

    @classmethod
    def get_instance(cls, port: int = 0) -> "UIServer":
        if cls._instance is None:
            cls._instance = UIServer(port)
        return cls._instance

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}/"

    def attach(self, storage: StatsStorage):
        """Attach a stats storage to visualize (UIServer.attach parity)."""
        if storage not in self._storages:
            self._storages.append(storage)

    def enable_remote_listener(self, storage: Optional[StatsStorage] = None,
                               attach: bool = True) -> StatsStorage:
        """Accept stats POSTed by RemoteUIStatsStorageRouter workers at
        /remoteReceive, routing them into `storage` (a fresh
        InMemoryStatsStorage when omitted). Mirrors
        PlayUIServer.enableRemoteListener / RemoteReceiverModule."""
        if storage is None:
            from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage
            storage = InMemoryStatsStorage()
        self._remote_storage = storage
        if attach:
            self.attach(storage)
        return storage

    def disable_remote_listener(self):
        self._remote_storage = None

    def receive_remote(self, records) -> Optional[int]:
        """Route one POSTed record batch into the remote-listener storage.
        Returns the record count, or None if remote receiving is off.
        The whole batch is parsed BEFORE anything is stored: a malformed
        record rejects the batch atomically, so the sender's whole-batch
        retry cannot duplicate a partially-committed prefix."""
        from deeplearning4j_tpu.ui.storage import StatsRecord
        if self._remote_storage is None:
            return None
        parsed = []
        for entry in records:
            entry = dict(entry)
            kind = entry.pop("kind", "update")
            parsed.append((kind, StatsRecord(**entry)))
        for kind, rec in parsed:
            if kind == "static":
                self._remote_storage.put_static_info(rec)
            else:
                self._remote_storage.put_update(rec)
        return len(parsed)

    def detach(self, storage: StatsStorage):
        if storage in self._storages:
            self._storages.remove(storage)

    # --------------------------------------------- t-SNE / word2vec views
    def post_tsne(self, session_id: str, points, labels=None):
        """Publish a 2D embedding to the /tsne view (TsneModule.java
        uploadFile/postFile parity). points: (N, 2) array-like or
        [[x, y, label?], ...]; labels: optional per-point labels."""
        out = []
        for i, p in enumerate(points):
            p = list(p)
            if labels is not None:
                out.append([float(p[0]), float(p[1]), str(labels[i])])
            elif len(p) > 2:
                out.append([float(p[0]), float(p[1]), str(p[2])])
            else:
                out.append([float(p[0]), float(p[1])])
        with self._tsne_lock:
            self._tsne_sessions[str(session_id)] = out

    def attach_word_vectors(self, word_vectors):
        """Attach a WordVectors/lookup table for the /word2vec nearest-
        neighbor view (NearestNeighborsQuery.java parity)."""
        self._word_vectors = word_vectors

    def nearest_words(self, word: str, n: int = 10) -> Dict:
        wv = self._word_vectors
        if wv is None:
            return {"error": "no word vectors attached "
                             "(UIServer.attach_word_vectors)"}
        if not word:
            return {"error": "empty query"}
        if hasattr(wv, "has_word") and not wv.has_word(word):
            return {"error": f"'{word}' not in vocabulary"}
        near = wv.words_nearest(word, top_n=n)
        return {"word": word, "nearest": [
            {"word": w, "similarity": float(wv.similarity(word, w))}
            for w in near]}

    # ----------------------------------------------------------- queries
    def session_ids(self):
        out = []
        for s in self._storages:
            out.extend(s.list_session_ids())
        return sorted(set(out))

    def session_data(self, sid: str, after: float) -> Dict:
        static = None
        updates = []
        for s in self._storages:
            for tid in s.list_type_ids(sid):
                for wid in s.list_worker_ids(sid):
                    st = s.get_static_info(sid, tid, wid)
                    if st is not None and static is None:
                        static = {"timestamp": st.timestamp, "data": st.data}
                    for r in s.get_all_updates_after(sid, tid, wid, after):
                        updates.append({"timestamp": r.timestamp,
                                        "data": r.data})
        updates.sort(key=lambda r: r["timestamp"])
        return {"static": static, "updates": updates[:500]}

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if UIServer._instance is self:
            UIServer._instance = None
