"""StatsListener — per-iteration training stats capture.

Parity: DL4J `deeplearning4j-ui-model/.../stats/BaseStatsListener.java:229-304`
(iterationDone: score, timing, memory, parameter/gradient/update histograms
and mean magnitudes, hooked via onGradientCalculation/onBackwardPass) plus
the static-info record (session start, model info, hardware).

TPU-native design: gradients/updates come from a dedicated jit variant of
the train step that returns the raw pytrees only on capture iterations
(MultiLayerNetwork._make_train_step with_stats=True) — the fast path
transfers nothing extra. Histograms/norms are computed host-side from the
fetched arrays; device memory comes from jax's per-device memory_stats().
"""
from __future__ import annotations

import json
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.train.listeners import TrainingListener
from deeplearning4j_tpu.ui.storage import (
    StatsRecord, StatsStorageRouter, new_session_id,
)

TYPE_ID = "StatsListener"        # DL4J uses the listener class name


def _leaf_paths(tree, prefix="") -> Dict[str, np.ndarray]:
    """Flatten a {layer: {param: array}} pytree into {"0/W": array} paths."""
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.update(_leaf_paths(tree[k], f"{prefix}{k}/"))
    elif tree is not None:
        arr = np.asarray(tree)
        if arr.size:
            out[prefix[:-1]] = arr
    return out


def _summarize(arrays: Dict[str, np.ndarray], n_bins: int,
               histograms: bool) -> Dict[str, dict]:
    summary = {}
    for path, a in arrays.items():
        a = a.astype("float64", copy=False).ravel()
        finite = a[np.isfinite(a)]
        entry = {
            "norm": float(np.linalg.norm(finite)),
            "mean_mag": float(np.abs(finite).mean()) if finite.size else 0.0,
            "n_non_finite": int(a.size - finite.size),
        }
        if histograms:
            # histogram over finite values only — a diverged run (NaN/Inf
            # grads) must not crash the fit loop; surfacing n_non_finite is
            # exactly what the dashboard needs at that moment
            if finite.size:
                lo, hi = float(finite.min()), float(finite.max())
                if lo == hi:
                    hi = lo + 1e-12
                counts, _ = np.histogram(finite, bins=n_bins,
                                         range=(lo, hi))
            else:
                lo, hi = 0.0, 0.0
                counts = np.zeros(n_bins, dtype=int)
            entry["hist"] = counts.tolist()
            entry["lo"], entry["hi"] = lo, hi
        summary[path] = entry
    return summary


def _device_memory() -> dict:
    mem = {}
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            mem["device_bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            mem["device_bytes_limit"] = int(stats.get("bytes_limit", 0))
    except Exception:
        pass
    try:
        import resource
        mem["host_max_rss_kb"] = int(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
    except Exception:
        pass
    return mem


class StatsListener(TrainingListener):
    """Captures score/timing/memory/param/grad/update stats into a
    StatsStorageRouter every `frequency` iterations.

    Usage (mirrors the reference's UIServer quickstart):
        storage = InMemoryStatsStorage()
        UIServer.get_instance().attach(storage)
        net.set_listeners(StatsListener(storage))
    """

    wants_gradients = True       # ask fit() for the stats train-step variant

    def __init__(self, router: StatsStorageRouter, frequency: int = 1,
                 session_id: Optional[str] = None,
                 worker_id: str = "worker-0", histograms: bool = True,
                 n_bins: int = 20):
        self.router = router
        self.frequency = max(int(frequency), 1)
        self.session_id = session_id or new_session_id()
        self.worker_id = worker_id
        self.histograms = histograms
        self.n_bins = int(n_bins)
        self._static_sent = False
        self._last_time: Optional[float] = None
        self._pending: Optional[dict] = None

    # -------------------------------------------------------------- hooks
    def should_capture(self, iteration: int) -> bool:
        return iteration % self.frequency == 0

    def on_gradients(self, model, iteration, epoch, grads, updates):
        """Receives the raw grad/update pytrees on capture iterations."""
        self._pending = {
            "gradients": _summarize(_leaf_paths(grads), self.n_bins,
                                    self.histograms),
            "updates": _summarize(_leaf_paths(updates), self.n_bins,
                                  self.histograms),
        }

    def iteration_done(self, model, iteration, epoch, score, etl_ms=0.0,
                       batch_size=0):
        if not self._static_sent:
            self._send_static(model)
        now = time.perf_counter()
        iter_ms = (now - self._last_time) * 1e3 if self._last_time else 0.0
        self._last_time = now
        if not self.should_capture(iteration):
            self._pending = None
            return
        data = {
            "iteration": int(iteration),
            "epoch": int(epoch),
            "score": float(score),
            "iter_ms": iter_ms,
            "etl_ms": float(etl_ms),
            "batch_size": int(batch_size),
            "samples_sec": (batch_size / (iter_ms / 1e3)
                            if iter_ms > 0 else 0.0),
            "memory": _device_memory(),
            "params": _summarize(_leaf_paths(model.params), self.n_bins,
                                 self.histograms),
        }
        if self._pending is not None:
            data.update(self._pending)
            self._pending = None
        self.router.put_update(StatsRecord(
            session_id=self.session_id, type_id=TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(), data=data))

    # ------------------------------------------------------------- static
    def _send_static(self, model):
        self._static_sent = True
        try:
            import jax
            devices = [f"{d.platform}:{d.id}" for d in jax.local_devices()]
        except Exception:
            devices = []
        layers: List[str] = [type(l).__name__
                             for l in getattr(model, "layers", [])]
        # per-layer drill-down table (TrainModule model tab): name, type,
        # param count and shapes, keyed the same way the update records
        # key their params ("0/W", "conv1/b", ...)
        params = getattr(model, "params", None) or {}
        detail = []
        if layers:
            named = [(str(i), type(l).__name__)
                     for i, l in enumerate(getattr(model, "layers", []))]
        else:       # ComputationGraph: vertices in topological order
            conf = getattr(model, "conf", None)
            vertices = getattr(conf, "vertices", {}) or {}
            named = [(name, type(vd.vertex).__name__)
                     for name, vd in vertices.items()]
        for key, ltype in named:
            # _leaf_paths handles nested trees (Bidirectional fwd/bwd etc.)
            # with the same path keys the update records use
            leaves = _leaf_paths(params.get(key, {}) or {})
            detail.append({
                "name": key,
                "type": ltype,
                "n_params": int(sum(a.size for a in leaves.values())),
                "shapes": {k: list(a.shape) for k, a in leaves.items()},
            })
        info = {
            "start_time": time.time(),
            "model_class": type(model).__name__,
            "num_params": int(model.num_params()),
            "num_layers": len(detail) if detail else len(layers),
            "layer_names": layers,
            "layers": detail,
            "devices": devices,
        }
        try:
            info["config_json"] = model.conf.to_json()
        except Exception:
            info["config_json"] = json.dumps({"error": "unserializable"})
        try:                      # layer table for the dashboard info card
            info["summary"] = model.summary()
        except Exception:
            pass
        self.router.put_static_info(StatsRecord(
            session_id=self.session_id, type_id=TYPE_ID,
            worker_id=self.worker_id, timestamp=time.time(), data=info))
