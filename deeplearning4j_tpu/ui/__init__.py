"""Observability UI stack — training stats capture, storage, dashboard.

Parity targets (SURVEY.md L7 / §2.8):
- StatsListener            <- deeplearning4j-ui-model/.../stats/BaseStatsListener.java:229-304
- StatsStorage / router    <- deeplearning4j-core/.../api/storage/StatsStorage.java,
                              InMemoryStatsStorage.java:20, FileStatsStorage.java:15
- UIServer dashboard       <- deeplearning4j-play/.../play/PlayUIServer.java +
                              module/train/TrainModule.java (overview/model/system tabs)

TPU-native redesign: no SBE binary codecs or Play framework — records are
JSON-serializable dataclasses, the file backend is append-only JSONL, and
the dashboard is a stdlib ThreadingHTTPServer serving one self-contained
HTML page that polls JSON endpoints and draws SVG charts (no external JS,
zero egress).
"""
from deeplearning4j_tpu.ui.storage import (
    FileStatsStorage, InMemoryStatsStorage, RemoteUIStatsStorageRouter,
    StatsRecord, StatsStorage, StatsStorageRouter,
)
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.server import UIServer

__all__ = [
    "FileStatsStorage", "InMemoryStatsStorage",
    "RemoteUIStatsStorageRouter", "StatsRecord",
    "StatsStorage", "StatsStorageRouter", "StatsListener", "UIServer",
]
