"""Dashboard internationalization.

Parity: the reference serves key=value message bundles per language from
`deeplearning4j-play/src/main/resources/dl4j_i18n/` (train.overview.*,
train.model.*, ... in de/en/ja/ko/ru/zh; DefaultI18N.java resolves them).
Here the catalog is an in-module dict served as JSON at /i18n?lang=xx;
pages mark translatable nodes with data-i18n attributes and apply the
catalog client-side. Unknown languages/keys fall back to English."""
from __future__ import annotations

from typing import Dict

LANGUAGES = ("en", "de", "ja", "ko", "ru", "zh")

# key -> {lang: text}; keys mirror the reference's naming scheme
_CATALOG: Dict[str, Dict[str, str]] = {
    "train.nav.overview": {
        "en": "overview", "de": "Übersicht", "ja": "概要", "ko": "개요",
        "ru": "обзор", "zh": "概览"},
    "train.nav.model": {
        "en": "model", "de": "Modell", "ja": "モデル", "ko": "모델",
        "ru": "модель", "zh": "模型"},
    "train.nav.system": {
        "en": "system", "de": "System", "ja": "システム", "ko": "시스템",
        "ru": "система", "zh": "系统"},
    "train.nav.tsne": {
        "en": "t-SNE", "de": "t-SNE", "ja": "t-SNE", "ko": "t-SNE",
        "ru": "t-SNE", "zh": "t-SNE"},
    "train.nav.word2vec": {
        "en": "word2vec", "de": "word2vec", "ja": "word2vec",
        "ko": "word2vec", "ru": "word2vec", "zh": "word2vec"},
    "train.overview.chart.score": {
        "en": "Score vs iteration", "de": "Score je Iteration",
        "ja": "スコア対イテレーション", "ko": "반복 당 점수",
        "ru": "Оценка по итерациям", "zh": "得分随迭代变化"},
    "train.overview.chart.throughput": {
        "en": "Samples/sec", "de": "Beispiele/Sek.", "ja": "サンプル/秒",
        "ko": "샘플/초", "ru": "примеров/сек", "zh": "样本/秒"},
    "train.overview.chart.memory": {
        "en": "Device memory (MB in use)",
        "de": "Gerätespeicher (MB belegt)", "ja": "デバイスメモリ (使用MB)",
        "ko": "장치 메모리 (사용 MB)", "ru": "Память устройства (МБ)",
        "zh": "设备内存（已用MB）"},
    "train.overview.chart.paramMag": {
        "en": "Parameter mean magnitudes (log10)",
        "de": "Mittlere Parameterbeträge (log10)",
        "ja": "パラメータ平均絶対値 (log10)",
        "ko": "파라미터 평균 크기 (log10)",
        "ru": "Средние величины параметров (log10)",
        "zh": "参数平均幅值 (log10)"},
    "train.overview.chart.ratio": {
        "en": "Update:param ratio (log10, healthy ~ -3)",
        "de": "Update:Parameter-Verhältnis (log10, gesund ~ -3)",
        "ja": "更新:パラメータ比 (log10, 健全 ~ -3)",
        "ko": "업데이트:파라미터 비율 (log10, 정상 ~ -3)",
        "ru": "Отношение обновл.:парам. (log10, норма ~ -3)",
        "zh": "更新:参数比 (log10, 健康值 ~ -3)"},
    "train.overview.info": {
        "en": "Model / session info", "de": "Modell-/Sitzungsinfo",
        "ja": "モデル / セッション情報", "ko": "모델 / 세션 정보",
        "ru": "Информация о модели/сессии", "zh": "模型 / 会话信息"},
    "train.overview.chart.gradHist": {
        "en": "Last gradient histogram", "de": "Letztes Gradienten-Histogramm",
        "ja": "最新の勾配ヒストグラム", "ko": "최근 그래디언트 히스토그램",
        "ru": "Гистограмма градиентов", "zh": "最新梯度直方图"},
    "train.model.layers": {
        "en": "Layers (click to select)",
        "de": "Schichten (zum Auswählen klicken)",
        "ja": "レイヤー (クリックで選択)", "ko": "레이어 (클릭하여 선택)",
        "ru": "Слои (щёлкните для выбора)", "zh": "层（点击选择）"},
    "train.model.paramMag": {
        "en": "Mean magnitude: parameters (log10)",
        "de": "Mittlerer Betrag: Parameter (log10)",
        "ja": "平均絶対値: パラメータ (log10)",
        "ko": "평균 크기: 파라미터 (log10)",
        "ru": "Средняя величина: параметры (log10)",
        "zh": "平均幅值：参数 (log10)"},
    "train.model.gradMag": {
        "en": "Mean magnitude: gradients (log10)",
        "de": "Mittlerer Betrag: Gradienten (log10)",
        "ja": "平均絶対値: 勾配 (log10)", "ko": "평균 크기: 그래디언트 (log10)",
        "ru": "Средняя величина: градиенты (log10)",
        "zh": "平均幅值：梯度 (log10)"},
    "tsne.title": {
        "en": "t-SNE embedding", "de": "t-SNE-Einbettung", "ja": "t-SNE埋め込み",
        "ko": "t-SNE 임베딩", "ru": "t-SNE вложение", "zh": "t-SNE嵌入"},
    "word2vec.title": {
        "en": "Nearest words", "de": "Nächste Wörter", "ja": "近傍単語",
        "ko": "가장 가까운 단어", "ru": "Ближайшие слова", "zh": "最近的词"},
    "word2vec.prompt": {
        "en": "word", "de": "Wort", "ja": "単語", "ko": "단어",
        "ru": "слово", "zh": "词"},
}


def tr(key: str, lang: str = "en") -> str:
    entry = _CATALOG.get(key, {})
    return entry.get(lang, entry.get("en", key))


def catalog(lang: str = "en") -> Dict[str, str]:
    lang = lang if lang in LANGUAGES else "en"
    return {k: tr(k, lang) for k in _CATALOG}
