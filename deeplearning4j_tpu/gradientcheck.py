"""Finite-difference gradient verification.

Parity target: DL4J `gradientcheck/GradientCheckUtil.java` (checkGradients
MLN :109-121, CG :331) and the gradient-check test strategy of
`deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/`
(SURVEY.md §4: the load-bearing correctness tool).

Role reversal vs DL4J: there, hand-written backprop is checked against
numeric differentiation; here, autodiff is the implementation and numeric
differentiation remains the oracle — same harness contract (max relative
error per parameter under a threshold), run in float64 on CPU like DL4J
insists on double precision.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_EPS = 1e-5
DEFAULT_MAX_REL_ERROR = 1e-3
DEFAULT_MIN_ABS_ERROR = 1e-8


@dataclasses.dataclass
class GradientCheckResult:
    passed: bool
    max_rel_error: float
    worst_param: str
    n_params_checked: int
    failures: list


def path_key(k):
    """Container key for a tree_flatten_with_path entry: DictKey -> .key,
    SequenceKey -> .idx, GetAttrKey -> .name."""
    for attr in ("key", "idx", "name"):
        if hasattr(k, attr):
            return getattr(k, attr)
    return k


def _rel_error(a: float, n: float, min_abs: float) -> float:
    if abs(a - n) < min_abs:
        return 0.0
    denom = abs(a) + abs(n)
    return abs(a - n) / denom if denom > 0 else 0.0


def check_gradients(model, features, labels, *,
                    eps: float = DEFAULT_EPS,
                    max_rel_error: float = DEFAULT_MAX_REL_ERROR,
                    min_abs_error: float = DEFAULT_MIN_ABS_ERROR,
                    max_per_param: int = 32,
                    features_mask=None, labels_mask=None,
                    seed: int = 12345,
                    print_results: bool = False) -> GradientCheckResult:
    """Compare autodiff gradients with central finite differences.

    Checks up to `max_per_param` randomly-chosen scalar entries per parameter
    array (DL4J checks every entry; sampling keeps CPU time sane for conv
    stacks — crank it up for release runs). Runs the loss in float64.
    """
    from jax import config as jax_config
    x64_was = jax_config.jax_enable_x64
    jax_config.update("jax_enable_x64", True)
    try:
        params64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), model.params)
        state64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64)
            if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating) else a,
            model.state)
        def _to64(v):
            if isinstance(v, (tuple, list)):
                return tuple(jnp.asarray(np.asarray(a), jnp.float64)
                             for a in v)
            return jnp.asarray(np.asarray(v), jnp.float64)

        def _mask64(v):
            if v is None:
                return None
            if isinstance(v, (tuple, list)):
                return tuple(None if m is None else jnp.asarray(m) for m in v)
            return jnp.asarray(v)

        x = _to64(features)
        y = _to64(labels)
        fm = _mask64(features_mask)
        lm = _mask64(labels_mask)

        # deterministic loss (train=True for dropout-free nets is fine; nets
        # with dropout should be checked with dropout=0, as DL4J requires)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        is_graph = isinstance(model, ComputationGraph)
        compute_saved = model._compute_dtype
        param_saved = model._param_dtype
        model._compute_dtype = jnp.dtype(jnp.float64)
        model._param_dtype = jnp.dtype(jnp.float64)
        try:
            @jax.jit
            def loss_fn(p):
                if is_graph:
                    xs = x if isinstance(x, tuple) else (x,)
                    ys = y if isinstance(y, tuple) else (y,)
                    fms = (None if fm is None
                           else fm if isinstance(fm, tuple) else (fm,))
                    lms = (None if lm is None
                           else lm if isinstance(lm, tuple) else (lm,))
                    loss, _ = model._score_fn(p, state64, xs, ys, fms, lms,
                                              False, None)
                else:
                    loss, _ = model._score_fn(p, state64, x, y, fm, lm,
                                              False, None)
                return loss

            analytic = jax.jit(jax.grad(loss_fn))(params64)
            rs = np.random.RandomState(seed)
            failures = []
            worst = ("", 0.0)
            checked = 0
            flat_params, treedef = jax.tree_util.tree_flatten_with_path(params64)
            leaves = [leaf for _, leaf in flat_params]
            analytic_leaves = jax.tree_util.tree_leaves(analytic)
            for leaf_idx, ((path, leaf), a_leaf) in enumerate(
                    zip(flat_params, analytic_leaves)):
                name = "/".join(str(path_key(k)) for k in path)
                a_grad = np.asarray(a_leaf)
                leaf_np = np.asarray(leaf)
                size = leaf_np.size
                idxs = np.arange(size) if size <= max_per_param else \
                    rs.choice(size, max_per_param, replace=False)
                for flat_i in idxs:
                    i = np.unravel_index(flat_i, leaf_np.shape)
                    orig = leaf_np[i]

                    def perturbed(v):
                        pl = leaf_np.copy()
                        pl[i] = v
                        new_leaves = list(leaves)
                        new_leaves[leaf_idx] = jnp.asarray(pl)
                        return jax.tree_util.tree_unflatten(treedef,
                                                            new_leaves)

                    lp = float(loss_fn(perturbed(orig + eps)))
                    lm_ = float(loss_fn(perturbed(orig - eps)))
                    numeric = (lp - lm_) / (2 * eps)
                    analytic_v = float(a_grad[i])
                    rel = _rel_error(analytic_v, numeric, min_abs_error)
                    checked += 1
                    if rel > worst[1]:
                        worst = (f"{name}[{i}]", rel)
                    if rel > max_rel_error:
                        failures.append((f"{name}[{i}]", analytic_v, numeric,
                                         rel))
            if print_results:
                print(f"gradient check: {checked} entries, worst "
                      f"{worst[0]} rel {worst[1]:.3e}, "
                      f"{len(failures)} failures")
            return GradientCheckResult(
                passed=not failures,
                max_rel_error=worst[1],
                worst_param=worst[0],
                n_params_checked=checked,
                failures=failures,
            )
        finally:
            model._compute_dtype = compute_saved
            model._param_dtype = param_saved
    finally:
        jax_config.update("jax_enable_x64", x64_was)
