"""Regression evaluation.

Parity target: DL4J eval/RegressionEvaluation.java:33 — per-column MSE, MAE,
RMSE, RSE, PC (Pearson correlation), R^2, streamed over batches.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, num_columns: Optional[int] = None):
        self._n = 0
        self._sum_err_sq = None
        self._sum_abs_err = None
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_label_pred = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                keep = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[keep], predictions[keep]
        elif mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]
        if self._sum_err_sq is None:
            c = labels.shape[-1]
            z = lambda: np.zeros(c, np.float64)
            self._sum_err_sq, self._sum_abs_err = z(), z()
            self._sum_label, self._sum_label_sq = z(), z()
            self._sum_pred, self._sum_pred_sq = z(), z()
            self._sum_label_pred = z()
        err = predictions - labels
        self._n += labels.shape[0]
        self._sum_err_sq += np.sum(err ** 2, axis=0)
        self._sum_abs_err += np.sum(np.abs(err), axis=0)
        self._sum_label += np.sum(labels, axis=0)
        self._sum_label_sq += np.sum(labels ** 2, axis=0)
        self._sum_pred += np.sum(predictions, axis=0)
        self._sum_pred_sq += np.sum(predictions ** 2, axis=0)
        self._sum_label_pred += np.sum(labels * predictions, axis=0)

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self._sum_err_sq[col] / self._n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self._sum_abs_err[col] / self._n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self._sum_err_sq[col] / self._n))

    def relative_squared_error(self, col: int = 0) -> float:
        mean_label = self._sum_label[col] / self._n
        ss_tot = self._sum_label_sq[col] - self._n * mean_label ** 2
        return float(self._sum_err_sq[col] / ss_tot) if ss_tot else float("inf")

    def pearson_correlation(self, col: int = 0) -> float:
        n = self._n
        num = n * self._sum_label_pred[col] - self._sum_label[col] * self._sum_pred[col]
        d1 = n * self._sum_label_sq[col] - self._sum_label[col] ** 2
        d2 = n * self._sum_pred_sq[col] - self._sum_pred[col] ** 2
        denom = np.sqrt(d1 * d2)
        return float(num / denom) if denom else 0.0

    def r_squared(self, col: int = 0) -> float:
        return 1.0 - self.relative_squared_error(col)

    def average_mean_squared_error(self) -> float:
        return float(np.mean(self._sum_err_sq / self._n))

    def stats(self) -> str:
        cols = len(self._sum_err_sq)
        lines = ["Column    MSE            MAE            RMSE           RSE            PC             R^2"]
        for c in range(cols):
            lines.append(
                f"col_{c}   {self.mean_squared_error(c):.6e}  "
                f"{self.mean_absolute_error(c):.6e}  "
                f"{self.root_mean_squared_error(c):.6e}  "
                f"{self.relative_squared_error(c):.6e}  "
                f"{self.pearson_correlation(c):.6e}  {self.r_squared(c):.6e}")
        return "\n".join(lines)
