"""Classification evaluation.

Parity target: DL4J eval/Evaluation.java:88 (confusion matrix, accuracy,
precision/recall/F1 incl. macro/micro averaging, top-N accuracy) and
eval/EvaluationBinary.java (per-output binary metrics for multi-label).
Accumulation is streaming (eval() per batch), matching DL4J's
iterator-driven evaluation; masks follow DL4J time-series semantics.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class ConfusionMatrix:
    """Dense integer confusion matrix (DL4J eval/ConfusionMatrix.java)."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.matrix = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.matrix, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def __str__(self):
        return str(self.matrix)


class Evaluation:
    def __init__(self, num_classes: Optional[int] = None,
                 label_names: Optional[List[str]] = None, top_n: int = 1):
        self._num_classes = num_classes
        self.label_names = label_names
        self.top_n = top_n
        self.confusion: Optional[ConfusionMatrix] = None
        self._top_n_correct = 0
        self._count = 0

    def _flatten(self, labels, predictions, mask):
        """Collapse (B,T,C)+mask time series to (N,C) rows (DL4J
        evalTimeSeries semantics)."""
        if labels.ndim == 3:
            b, t, c = labels.shape
            labels = labels.reshape(b * t, c)
            predictions = predictions.reshape(b * t, c)
            if mask is not None:
                keep = np.asarray(mask).reshape(b * t) > 0
                labels, predictions = labels[keep], predictions[keep]
        return labels, predictions

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        labels, predictions = self._flatten(labels, predictions, mask)
        if labels.ndim == 1 or labels.shape[-1] == 1:
            actual = labels.astype(np.int64).reshape(-1)
            nc = self._num_classes or predictions.shape[-1]
        else:
            actual = np.argmax(labels, axis=-1)
            nc = self._num_classes or labels.shape[-1]
        pred = np.argmax(predictions, axis=-1)
        if self.confusion is None:
            self.confusion = ConfusionMatrix(nc)
        self.confusion.add(actual, pred)
        self._count += len(actual)
        if self.top_n > 1:
            top = np.argsort(-predictions, axis=-1)[:, :self.top_n]
            self._top_n_correct += int(np.sum(top == actual[:, None]))

    # ------------------------------------------------------------- metrics
    def _tp(self):
        return np.diag(self.confusion.matrix).astype(np.float64)

    def _row(self):
        return self.confusion.matrix.sum(axis=1).astype(np.float64)  # actual counts

    def _col(self):
        return self.confusion.matrix.sum(axis=0).astype(np.float64)  # predicted counts

    def accuracy(self) -> float:
        m = self.confusion.matrix
        total = m.sum()
        return float(np.diag(m).sum() / total) if total else 0.0

    def top_n_accuracy(self) -> float:
        return self._top_n_correct / self._count if self._count else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        tp, col = self._tp(), self._col()
        if cls is not None:
            return float(tp[cls] / col[cls]) if col[cls] else 0.0
        valid = col > 0
        return float(np.mean(tp[valid] / col[valid])) if valid.any() else 0.0

    def recall(self, cls: Optional[int] = None) -> float:
        tp, row = self._tp(), self._row()
        if cls is not None:
            return float(tp[cls] / row[cls]) if row[cls] else 0.0
        valid = row > 0
        return float(np.mean(tp[valid] / row[valid])) if valid.any() else 0.0

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, cls: int) -> float:
        m = self.confusion.matrix
        fp = m[:, cls].sum() - m[cls, cls]
        tn = m.sum() - m[cls, :].sum() - m[:, cls].sum() + m[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def matthews_correlation(self, cls: int) -> float:
        m = self.confusion.matrix
        tp = m[cls, cls]
        fp = m[:, cls].sum() - tp
        fn = m[cls, :].sum() - tp
        tn = m.sum() - tp - fp - fn
        denom = np.sqrt(float((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn)))
        return float((tp * tn - fp * fn) / denom) if denom else 0.0

    def stats(self) -> str:
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.confusion.num_classes}",
            f" Examples:        {self._count}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} accuracy: {self.top_n_accuracy():.4f}")
        lines.append("=================Confusion Matrix=================")
        lines.append(str(self.confusion))
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output binary metrics for multi-label sigmoid outputs
    (DL4J eval/EvaluationBinary.java)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels)
        pred = (np.asarray(predictions) >= self.threshold).astype(np.int64)
        lab = (labels >= 0.5).astype(np.int64)
        if self.tp is None:
            n = labels.shape[-1]
            self.tp = np.zeros(n, np.int64)
            self.fp = np.zeros(n, np.int64)
            self.tn = np.zeros(n, np.int64)
            self.fn = np.zeros(n, np.int64)
        w = np.ones(labels.shape) if mask is None else np.asarray(mask)
        if w.ndim < labels.ndim:
            w = w[..., None]
        axes = tuple(range(labels.ndim - 1))
        self.tp += np.sum((pred == 1) & (lab == 1) * (w > 0), axis=axes).astype(np.int64)
        self.fp += np.sum((pred == 1) & (lab == 0) * (w > 0), axis=axes).astype(np.int64)
        self.tn += np.sum((pred == 0) & (lab == 0) * (w > 0), axis=axes).astype(np.int64)
        self.fn += np.sum((pred == 0) & (lab == 1) * (w > 0), axis=axes).astype(np.int64)

    def accuracy(self, i: int) -> float:
        tot = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / tot) if tot else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0
