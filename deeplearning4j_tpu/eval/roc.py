"""ROC / AUC and probability-calibration evaluation.

Parity targets: DL4J eval/ROC.java:58 (binary ROC/AUC + PR curve),
eval/ROCMultiClass.java (one-vs-all per class), and
eval/EvaluationCalibration.java (reliability diagram + histograms).
Exact (threshold-free) AUC via rank statistics — equivalent to DL4J's
`thresholdSteps=0` exact mode.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np


def _auc_exact(labels: np.ndarray, scores: np.ndarray) -> float:
    """Exact ROC-AUC by the rank-sum (Mann-Whitney U) method."""
    pos = scores[labels > 0.5]
    neg = scores[labels <= 0.5]
    if len(pos) == 0 or len(neg) == 0:
        return 0.0
    order = np.argsort(np.concatenate([pos, neg]), kind="mergesort")
    ranks = np.empty(len(order), np.float64)
    combined = np.concatenate([pos, neg])[order]
    # average ranks for ties
    i = 0
    while i < len(combined):
        j = i
        while j + 1 < len(combined) and combined[j + 1] == combined[i]:
            j += 1
        ranks[i:j + 1] = (i + j) / 2.0 + 1.0
        i = j + 1
    inv = np.empty(len(order), np.int64)
    inv[order] = np.arange(len(order))
    pos_ranks = ranks[inv[:len(pos)]]
    u = pos_ranks.sum() - len(pos) * (len(pos) + 1) / 2.0
    return float(u / (len(pos) * len(neg)))


class ROC:
    """Binary ROC (DL4J eval/ROC.java). Accumulates scores; curves/AUC exact."""

    def __init__(self):
        self._labels: List[np.ndarray] = []
        self._scores: List[np.ndarray] = []

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2 and labels.shape[1] == 2:
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        self._labels.append(labels.reshape(-1).astype(np.float64))
        self._scores.append(predictions.reshape(-1).astype(np.float64))

    def _all(self):
        return np.concatenate(self._labels), np.concatenate(self._scores)

    def calculate_auc(self) -> float:
        labels, scores = self._all()
        return _auc_exact(labels, scores)

    def calculate_aucpr(self) -> float:
        """Area under precision-recall curve (trapezoidal on exact curve)."""
        labels, scores = self._all()
        order = np.argsort(-scores, kind="mergesort")
        labels = labels[order]
        tp = np.cumsum(labels)
        fp = np.cumsum(1 - labels)
        total_pos = labels.sum()
        if total_pos == 0:
            return 0.0
        precision = tp / np.maximum(tp + fp, 1)
        recall = tp / total_pos
        return float(np.trapezoid(precision, recall))

    def roc_curve(self, steps: int = 100):
        labels, scores = self._all()
        thresholds = np.linspace(0, 1, steps + 1)
        total_pos = max(labels.sum(), 1)
        total_neg = max((1 - labels).sum(), 1)
        tpr = [(scores >= t)[labels > 0.5].sum() / total_pos for t in thresholds]
        fpr = [(scores >= t)[labels <= 0.5].sum() / total_neg for t in thresholds]
        return np.array(fpr), np.array(tpr), thresholds


class ROCMultiClass:
    """One-vs-all ROC per class (DL4J eval/ROCMultiClass.java)."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        nc = labels.shape[-1]
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(nc)]
        for c in range(nc):
            self._rocs[c].eval(labels[..., c], predictions[..., c])

    def calculate_auc(self, cls: int) -> float:
        return self._rocs[cls].calculate_auc()

    def calculate_average_auc(self) -> float:
        return float(np.mean([r.calculate_auc() for r in self._rocs]))


class EvaluationCalibration:
    """Reliability diagram + label/prediction histograms
    (DL4J eval/EvaluationCalibration.java)."""

    def __init__(self, reliability_bins: int = 10):
        self.bins = reliability_bins
        self._bin_counts = np.zeros(reliability_bins, np.int64)
        self._bin_pos = np.zeros(reliability_bins, np.int64)
        self._bin_prob_sum = np.zeros(reliability_bins, np.float64)

    def eval(self, labels: np.ndarray, predictions: np.ndarray):
        labels = np.asarray(labels).reshape(-1)
        probs = np.asarray(predictions).reshape(-1)
        idx = np.clip((probs * self.bins).astype(int), 0, self.bins - 1)
        np.add.at(self._bin_counts, idx, 1)
        np.add.at(self._bin_pos, idx, (labels > 0.5).astype(np.int64))
        np.add.at(self._bin_prob_sum, idx, probs)

    def reliability_diagram(self):
        """Returns (mean predicted prob, empirical frequency) per bin."""
        with np.errstate(invalid="ignore", divide="ignore"):
            mean_prob = self._bin_prob_sum / np.maximum(self._bin_counts, 1)
            freq = self._bin_pos / np.maximum(self._bin_counts, 1)
        return mean_prob, freq

    def expected_calibration_error(self) -> float:
        mean_prob, freq = self.reliability_diagram()
        total = max(self._bin_counts.sum(), 1)
        w = self._bin_counts / total
        return float(np.sum(w * np.abs(mean_prob - freq)))
