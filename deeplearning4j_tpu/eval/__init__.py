from deeplearning4j_tpu.eval.evaluation import Evaluation, ConfusionMatrix, EvaluationBinary
from deeplearning4j_tpu.eval.regression import RegressionEvaluation
from deeplearning4j_tpu.eval.roc import ROC, ROCMultiClass, EvaluationCalibration

__all__ = ["Evaluation", "ConfusionMatrix", "EvaluationBinary",
           "RegressionEvaluation", "ROC", "ROCMultiClass",
           "EvaluationCalibration"]
