"""sklearn-style estimator wrappers — the `dl4j-spark-ml` analog.

Parity target: `deeplearning4j-scaleout/spark/dl4j-spark-ml/src/main/
spark-2/scala/org/deeplearning4j/spark/ml/impl/SparkDl4jNetwork.scala`
(a Spark ML Estimator producing a Model with transform/predict) and
`AutoEncoder.scala` (unsupervised feature transformer). The reference
plugs DL4J training into Spark's ML-pipeline contract; the honest modern
analog on this stack is scikit-learn's estimator contract — fit/predict/
predict_proba/transform/get_params — so a network drops into
sklearn.pipeline.Pipeline, GridSearchCV, cross_val_score, etc.

Estimators subclass sklearn's BaseEstimator when sklearn is importable
(get_params/set_params/clone support); otherwise a minimal stand-in keeps
the same duck-typed surface, so sklearn is an optional integration, not a
dependency.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

try:                                    # optional integration
    from sklearn.base import (
        BaseEstimator, ClassifierMixin, RegressorMixin, TransformerMixin,
    )
except ImportError:                     # pragma: no cover
    class BaseEstimator:                # minimal get/set_params stand-in
        def get_params(self, deep=True):
            return {k: v for k, v in self.__dict__.items()
                    if not k.startswith("_") and not k.endswith("_")}

        def set_params(self, **params):
            for k, v in params.items():
                setattr(self, k, v)
            return self

    class ClassifierMixin:
        pass

    class RegressorMixin:
        pass

    class TransformerMixin:
        def fit_transform(self, X, y=None, **kw):
            return self.fit(X, y, **kw).transform(X)


def _default_conf(n_features: int, n_out: int, hidden: tuple, lr: float,
                  seed: int, activation: str, loss: str):
    """ReLU MLP scaffold with a configurable head (softmax/mcxent for the
    classifier, identity/mse for the regressor)."""
    from deeplearning4j_tpu.nn.conf import (
        InputType, NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.updaters import Adam
    b = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(lr))
         .list())
    for h in hidden:
        b.layer(DenseLayer(n_out=int(h), activation="relu"))
    b.layer(OutputLayer(n_out=n_out, activation=activation, loss=loss))
    return b.set_input_type(InputType.feed_forward(n_features)).build()


class _NetworkEstimator(BaseEstimator):
    """Shared fit plumbing: builds (or accepts) a MultiLayerConfiguration,
    trains a MultiLayerNetwork, exposes the fitted net as `network_`."""

    def _fit_network(self, conf, X, Y):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        net = MultiLayerNetwork(conf).init()
        net.fit((np.asarray(X, np.float32), np.asarray(Y, np.float32)),
                epochs=self.epochs, batch_size=self.batch_size,
                scan_steps=self.scan_steps)
        self.network_ = net
        return self

    def _check_fitted(self):
        if not hasattr(self, "network_"):
            raise RuntimeError(
                f"{type(self).__name__} is not fitted yet — call fit first")

    # ------------------------------------------------- pickle / joblib
    # the fitted network holds optax closures that don't pickle; route
    # persistence through the checkpoint-zip format instead so
    # pickle/joblib.dump of a fitted estimator Just Works
    def __getstate__(self):
        state = self.__dict__.copy()
        net = state.pop("network_", None)
        if net is not None:
            import io

            from deeplearning4j_tpu.util.serialization import save_model
            buf = io.BytesIO()
            save_model(net, buf)
            state["_network_blob_"] = buf.getvalue()
        return state

    def __setstate__(self, state):
        blob = state.pop("_network_blob_", None)
        self.__dict__.update(state)
        if blob is not None:
            import io

            from deeplearning4j_tpu.util.serialization import load_model
            self.network_ = load_model(io.BytesIO(blob))


class DL4JClassifier(ClassifierMixin, _NetworkEstimator):
    """Classifier estimator (SparkDl4jNetwork.scala's Estimator role).

    `conf` may be a ready MultiLayerConfiguration (its output head defines
    the classes) or None — then a ReLU MLP softmax head is built from
    `hidden`/`learning_rate` at fit time, sized to the data.

    >>> clf = DL4JClassifier(hidden=(32,), epochs=20)
    >>> clf.fit(X, y).predict(X)          # y: int class labels
    >>> Pipeline([("scale", StandardScaler()), ("net", clf)]).fit(X, y)
    """

    def __init__(self, conf=None, hidden=(64,), learning_rate=1e-2,
                 epochs: int = 10, batch_size: int = 32,
                 scan_steps: Optional[int] = None, seed: int = 0):
        self.conf = conf
        self.hidden = hidden
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.scan_steps = scan_steps
        self.seed = seed

    def fit(self, X, y):
        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        idx = {c: i for i, c in enumerate(self.classes_)}
        Y = np.eye(len(self.classes_), dtype=np.float32)[
            np.vectorize(idx.get)(y)]
        conf = self.conf or _default_conf(
            X.shape[1], len(self.classes_), tuple(self.hidden),
            self.learning_rate, self.seed, "softmax", "mcxent")
        return self._fit_network(conf, X, Y)

    def predict_proba(self, X):
        self._check_fitted()
        return np.asarray(self.network_.output(
            np.asarray(X, np.float32)))

    def predict(self, X):
        proba = self.predict_proba(X)   # raises first when unfitted
        return self.classes_[proba.argmax(axis=1)]


class DL4JRegressor(RegressorMixin, _NetworkEstimator):
    """Regressor estimator: identity/MSE head counterpart."""

    def __init__(self, conf=None, hidden=(64,), learning_rate=1e-2,
                 epochs: int = 10, batch_size: int = 32,
                 scan_steps: Optional[int] = None, seed: int = 0):
        self.conf = conf
        self.hidden = hidden
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.scan_steps = scan_steps
        self.seed = seed

    def fit(self, X, y):
        X = np.asarray(X, np.float32)
        Y = np.asarray(y, np.float32)
        if Y.ndim == 1:
            Y = Y[:, None]
        self.n_outputs_ = Y.shape[1]
        conf = self.conf or _default_conf(
            X.shape[1], self.n_outputs_, tuple(self.hidden),
            self.learning_rate, self.seed, "identity", "mse")
        return self._fit_network(conf, X, Y)

    def predict(self, X):
        self._check_fitted()
        out = np.asarray(self.network_.output(np.asarray(X, np.float32)))
        return out[:, 0] if self.n_outputs_ == 1 else out


class AutoEncoderTransformer(TransformerMixin, _NetworkEstimator):
    """Unsupervised feature transformer (AutoEncoder.scala /
    AutoEncoderWrapper.scala): fit trains a dense autoencoder on X via
    layerwise pretraining; transform returns the bottleneck encoding."""

    def __init__(self, n_components: int = 16, learning_rate: float = 1e-2,
                 epochs: int = 10, batch_size: int = 32,
                 scan_steps: Optional[int] = None, seed: int = 0):
        self.n_components = n_components
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.batch_size = batch_size
        self.scan_steps = scan_steps
        self.seed = seed

    def fit(self, X, y=None):
        from deeplearning4j_tpu.nn.conf import (
            InputType, NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import AutoEncoder, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        from deeplearning4j_tpu.nn.updaters import Adam
        X = np.asarray(X, np.float32)
        conf = (NeuralNetConfiguration.Builder().seed(self.seed)
                .updater(Adam(self.learning_rate)).list()
                .layer(AutoEncoder(n_out=int(self.n_components),
                                   activation="tanh"))
                .layer(OutputLayer(n_out=X.shape[1], activation="identity",
                                   loss="mse"))
                .set_input_type(InputType.feed_forward(X.shape[1]))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit_pretrain((X, X), epochs=self.epochs,
                         batch_size=self.batch_size)
        self.network_ = net
        return self

    def transform(self, X):
        self._check_fitted()
        acts = self.network_.feed_forward(np.asarray(X, np.float32))
        return np.asarray(acts[0])      # bottleneck (AutoEncoder) output
