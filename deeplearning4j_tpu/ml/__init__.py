from deeplearning4j_tpu.ml.estimators import (
    AutoEncoderTransformer, DL4JClassifier, DL4JRegressor,
)

__all__ = ["DL4JClassifier", "DL4JRegressor", "AutoEncoderTransformer"]
