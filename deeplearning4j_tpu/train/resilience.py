"""Fault-tolerant training: resilient fit loop with atomic checkpoints,
auto-resume, preemption handling, and a per-step fault policy.

The north-star deployment is a preemptible TPU fleet where jobs are
killed routinely (spot preemption, maintenance, transport flaps) and a
single NaN step must not burn the run. `parallel/distributed.py`
declares the stance — "failure handling = checkpoint + restart; we layer
checkpoint/resume on top" — and this module is that layer:

- **Atomic, manifest-tracked checkpoints.** Every checkpoint zip is
  written tmp-then-`os.replace` (util/serialization.save_model atomic
  mode) and recorded in a `manifest.json` (itself atomically replaced)
  with a SHA-256 integrity hash. A kill at ANY instant leaves either the
  previous complete manifest/checkpoint set or the new one — never a
  truncated zip that a resume would trip over. Checkpoints carry params,
  updater (optimizer) state, layer state, iteration/epoch counters, the
  live RNG key, the position in the data stream, and the fitted data
  normalizer. `keep_last` pruning removes only manifest-tracked files —
  foreign files in the directory are never touched.

- **Auto-resume.** `fit()` restores the newest manifest entry whose hash
  verifies (corrupted/missing files fall back to the next-newest),
  fast-forwards the data iterator to the recorded epoch/step, and
  continues the RNG stream from the stored key — a killed-and-resumed
  run reaches bitwise-identical parameters (and updater state) to an
  uninterrupted one, provided the data source is deterministic.

- **Preemption.** SIGTERM/SIGINT set a flag; at the next step boundary
  the trainer writes a final checkpoint and shuts down cleanly
  (`FitReport.preempted=True`). Re-running the same command resumes.

- **Per-step fault policy** (`FaultPolicy`): transient errors retry with
  jittered exponential backoff from a pre-step host snapshot (a retried
  step is bitwise-identical to an unfaulted one — same RNG, same batch);
  non-finite losses skip the step (snapshot restore) with a
  consecutive-skip abort threshold; score explosions are detected by an
  integrated `DivergenceListener`. Unrecoverable divergence restores the
  newest good checkpoint instead of leaving NaN params behind.

`util/faults.py` injects deterministic faults through the same step
boundaries, so every path above is testable (tests/test_resilience.py,
tools/chaos_fit.py). See docs/FAULT_TOLERANCE.md for the operational
guide.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import math
import os
import random
import signal
import threading
import time
import weakref
import zipfile
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import goodput
from deeplearning4j_tpu.train.listeners import (
    DivergenceListener, TrainingDivergedError,
)
from deeplearning4j_tpu.util.faults import FaultInjector, TransientFaultError

log = logging.getLogger("deeplearning4j_tpu")


# --------------------------------------------------------------------- policy
@dataclasses.dataclass
class FaultPolicy:
    """Per-step fault handling knobs (docs/FAULT_TOLERANCE.md)."""

    #: transient-error retry: attempts beyond the first, with jittered
    #: exponential backoff in [backoff_base, backoff_max] seconds.
    max_retries: int = 3
    backoff_base: float = 0.05
    backoff_max: float = 2.0
    #: exception classes treated as retryable. Everything else propagates.
    transient_errors: Tuple = (TransientFaultError, ConnectionError,
                               TimeoutError, OSError)
    #: NaN/Inf loss -> restore the pre-step snapshot and skip the batch.
    skip_nonfinite: bool = True
    #: consecutive skipped steps beyond which training is declared
    #: unrecoverable (the last good checkpoint is restored).
    max_consecutive_skips: int = 3
    #: "restore": restore newest good checkpoint, log, stop the fit.
    #: "raise": restore, then raise TrainingDivergedError.
    on_unrecoverable: str = "restore"
    #: score-explosion detection via DivergenceListener (None disables).
    explosion_factor: Optional[float] = 1e4
    explosion_window: int = 20
    #: seed for the backoff jitter stream (determinism in tests).
    seed: int = 0

    @property
    def guards_steps(self) -> bool:
        """True when a pre-step host snapshot is kept (needed to undo a
        faulted step). Costs one host copy of params/opt/state per step —
        disable both knobs for maximum-throughput unguarded fits."""
        return self.skip_nonfinite or self.max_retries > 0


@dataclasses.dataclass
class FitReport:
    """What happened during a resilient fit (returned by
    ResilientTrainer.fit; the trained model lives on the network)."""

    applied_steps: int = 0
    skipped_steps: int = 0
    retries: int = 0
    checkpoints_written: int = 0
    checkpoints_blessed: int = 0
    resumed_from: Optional[str] = None
    preempted: bool = False
    diverged: bool = False
    restored_checkpoint: Optional[str] = None
    final_score: Optional[float] = None
    #: goodput-ledger session summary, when `monitor.goodput` is enabled:
    #: the fit's wall-clock split over the closed category set (so a
    #: preempt->resume run accounts its replay as overhead, not compute)
    goodput_pct: Optional[float] = None
    time_by_category: Optional[dict] = None


class _Unrecoverable(Exception):
    """Internal control flow: divergence beyond the fault policy's
    tolerance; fit() translates it into restore-last-good semantics."""


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:     # EPERM etc.: exists but not ours
        return True
    return True


# --------------------------------------------------------- checkpoint manager
class CheckpointManager:
    """Atomic, manifest-tracked checkpoint directory.

    Layout:
        <dir>/manifest.json          atomic (tmp + os.replace), hash index
        <dir>/ckpt_000042.zip        save_model zip + resilience extras

    The manifest is the source of truth: `latest_valid()` walks it
    newest-first and SHA-256-verifies each candidate, so a truncated or
    bit-rotted file is skipped with a warning instead of crashing the
    resume. Pruning removes only manifest-tracked files — anything else
    in the directory (foreign checkpoints, notes, exports) is preserved.
    """

    MANIFEST = "manifest.json"
    BLESSED = "blessed.json"

    def __init__(self, directory: str, keep_last: int = 3,
                 prefix: str = "ckpt"):
        self.dir = directory
        self.keep_last = max(1, int(keep_last))
        self.prefix = prefix
        os.makedirs(directory, exist_ok=True)
        # a kill mid-save leaves a *.zip.tmp.<pid> partial. Sweep only
        # leftovers whose writing process is gone — on a shared checkpoint
        # dir another live process may be mid-save right now, and deleting
        # its tmp file would break its os.replace.
        for name in os.listdir(directory):
            if not (name.startswith(prefix) and ".zip.tmp." in name):
                continue
            try:
                pid = int(name.rsplit(".", 1)[-1])
            except ValueError:
                continue
            if pid != os.getpid() and _pid_alive(pid):
                continue
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass

    # ------------------------------------------------------------- manifest
    def _manifest_path(self) -> str:
        return os.path.join(self.dir, self.MANIFEST)

    def _read_manifest(self) -> dict:
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"version": 1, "next_ordinal": 0, "checkpoints": []}

    def _write_manifest(self, manifest: dict):
        tmp = self._manifest_path() + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp, self._manifest_path())

    @staticmethod
    def _sha256(path: str) -> str:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    # ----------------------------------------------------------------- save
    def save(self, model, extra: dict) -> str:
        """Write one checkpoint atomically and record it in the manifest.
        `extra` (JSON-serializable) lands in the zip as resilience.json —
        the RNG key / stream position / normalizer the resume needs."""
        from deeplearning4j_tpu.util.serialization import save_model
        manifest = self._read_manifest()
        ordinal = int(manifest.get("next_ordinal", 0))
        fname = f"{self.prefix}_{ordinal:06d}.zip"
        path = os.path.join(self.dir, fname)
        save_model(model, path, atomic=True,
                   extra_entries={"resilience.json": json.dumps(extra)})
        manifest["checkpoints"].append({
            "file": fname,
            "sha256": self._sha256(path),
            "iteration": int(model.iteration_count),
            "epoch": int(model.epoch_count),
            "step_in_epoch": int(extra.get("step_in_epoch", 0)),
            "time": time.time(),
        })
        manifest["next_ordinal"] = ordinal + 1
        # keep_last pruning: drop only files THIS manifest tracks — and
        # never the blessed (serving-eligible) one: the rollout watcher
        # may not have deployed it yet, and pruning it would leave
        # blessed.json pointing at nothing
        blessed = self._blessed_file()
        while len(manifest["checkpoints"]) > self.keep_last:
            prunable = [e for e in manifest["checkpoints"][:-self.keep_last]
                        if e["file"] != blessed]
            if not prunable:
                break
            old = prunable[0]
            manifest["checkpoints"].remove(old)
            try:
                os.remove(os.path.join(self.dir, old["file"]))
            except OSError:
                pass
        self._write_manifest(manifest)
        return path

    # ---------------------------------------------------------------- bless
    def _blessed_path(self) -> str:
        return os.path.join(self.dir, self.BLESSED)

    def _blessed_file(self) -> Optional[str]:
        try:
            with open(self._blessed_path()) as f:
                return json.load(f).get("file")
        except (OSError, ValueError):
            return None

    def bless(self, path: str, metrics: Optional[dict] = None) -> str:
        """Mark a checkpoint serving-eligible: atomically (re)write
        <dir>/blessed.json naming the file, its SHA-256, and the eval
        metrics that justified the blessing. serving/rollout.py tails
        this manifest — blessing is the eval gate between "the trainer
        wrote a checkpoint" and "the fleet may canary it"."""
        fname = os.path.basename(path)
        doc = {
            "version": 1,
            "file": fname,
            "path": os.path.abspath(path),
            "sha256": self._sha256(path),
            "blessed_at": time.time(),
            "metrics": dict(metrics or {}),
        }
        for entry in self._read_manifest().get("checkpoints", []):
            if entry["file"] == fname:
                doc["iteration"] = entry["iteration"]
                doc["epoch"] = entry["epoch"]
                break
        tmp = self._blessed_path() + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, self._blessed_path())
        monitor.counter("resilience_checkpoints_blessed_total",
                        "Checkpoints marked serving-eligible "
                        "(blessed.json writes)").inc()
        log.info("checkpoint blessed for serving: %s (metrics %s)",
                 fname, doc["metrics"])
        return self._blessed_path()

    # --------------------------------------------------------------- resume
    def latest_valid(self) -> Optional[dict]:
        """Newest manifest entry whose file exists and hash verifies;
        invalid entries are skipped (fall back to the next-newest)."""
        manifest = self._read_manifest()
        for entry in reversed(manifest.get("checkpoints", [])):
            path = os.path.join(self.dir, entry["file"])
            if not os.path.exists(path):
                log.warning("checkpoint %s missing; falling back", path)
                continue
            try:
                if self._sha256(path) != entry["sha256"]:
                    log.warning("checkpoint %s failed integrity check; "
                                "falling back", path)
                    continue
            except OSError as e:
                log.warning("checkpoint %s unreadable (%s); falling back",
                            path, e)
                continue
            return {**entry, "path": path}
        return None

    def restore_into(self, model, path: str) -> dict:
        """Load a checkpoint INTO an existing (initialized) model and
        return the resilience extras dict ({} for plain save_model zips)."""
        from deeplearning4j_tpu.util.serialization import (
            _npz_bytes_to_tree, _restore_like,
        )
        if model.params is None:
            model.init()
        with zipfile.ZipFile(path, "r") as zf:
            meta = json.loads(zf.read("metadata.json"))
            conf_json = zf.read("configuration.json").decode()
            if conf_json != model.conf.to_json():
                log.warning("resuming %s into a model whose configuration "
                            "differs from the checkpoint's", path)
            model.params = _restore_like(
                model.params, _npz_bytes_to_tree(zf.read("coefficients.npz")))
            model.state = _restore_like(
                model.state, _npz_bytes_to_tree(zf.read("state.npz")))
            model.iteration_count = int(meta.get("iteration_count", 0))
            model.epoch_count = int(meta.get("epoch_count", 0))
            names = zf.namelist()
            if "updaterState.bin" in names:
                from flax import serialization as fser
                from deeplearning4j_tpu.util.params import own_tree
                # owned copies: from_bytes yields numpy leaves which the
                # donated train step must never alias (owned_leaf)
                model.opt_state = own_tree(fser.from_bytes(
                    model.opt_state, zf.read("updaterState.bin")))
            extra = json.loads(zf.read("resilience.json")) \
                if "resilience.json" in names else {}
        return extra


# ----------------------------------------------------------------- preemption
class PreemptionGuard:
    """SIGTERM/SIGINT -> request a clean stop at the next step boundary.

    Installed only on the main thread (signal.signal requires it); the
    previous handlers are restored on exit. A second SIGINT while the
    final checkpoint is being written still raises KeyboardInterrupt —
    the guard chains to the previous handler after the first delivery —
    so an operator can always force-quit."""

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = signals
        self.requested = False
        self.signum: Optional[int] = None
        self._old: dict = {}

    def _handler(self, signum, frame):
        if self.requested:
            old = self._old.get(signum)
            if callable(old):
                old(signum, frame)
            return
        self.requested = True
        self.signum = signum
        log.warning("received signal %d: checkpointing and shutting down "
                    "at the next step boundary", signum)

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                try:
                    self._old[s] = signal.signal(s, self._handler)
                except (ValueError, OSError):  # non-main thread / exotic os
                    pass
        return self

    def __exit__(self, *exc):
        for s, old in self._old.items():
            try:
                signal.signal(s, old)
            except (ValueError, OSError):
                pass
        self._old = {}
        return False


# ------------------------------------------------------------------- drivers
def _host_copy(tree):
    # forced host copies: the live arrays are donated by the next step —
    # np.asarray could alias the soon-deleted buffers on CPU backends
    return jax.tree_util.tree_map(lambda a: np.array(a, copy=True), tree)


class _NetDriver:
    """Per-call step execution for MultiLayerNetwork — the same compiled
    step, staging, and RNG stream as MultiLayerNetwork._fit_epoch."""

    rng_mult = 7919

    #: ledger name of the compiled step this driver executes
    ledger_program = "mln/train_step"

    #: whether this driver resolves the process-wide GSPMD plan
    #: (parallel/plan.use_mesh) onto the net — the _WrapperDriver turns
    #: this off because ParallelWrapper manages its own plan/placement
    _uses_plan = True

    def __init__(self, net):
        self.net = net
        self._ledger_rec = None        # latest monitor.xla program record
        self._ledger_fresh = False     # last capture was a debut
        self._ledger_pending = None    # deferred capture args (see below)

    def capture_ledger(self):
        """Run the capture step() deferred, OUTSIDE the caller's attempt
        clock — the first sight of a program pays an AOT lower+compile,
        which must not inflate step_secs / train_step_seconds. Dict-hit
        after the first call per program. Marks _ledger_fresh so the
        caller can skip feeding the debut step's compile-inflated wall
        time to the MFU accountant."""
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        pending, self._ledger_pending = self._ledger_pending, None
        if pending is None:
            return
        cache, key, name, fn, args, bs = pending
        self._ledger_fresh = key not in cache
        self._ledger_rec = xla_ledger.capture_cached(
            cache, key, name, fn, args, examples_per_call=bs)

    def prepare(self):
        from deeplearning4j_tpu.util import params as param_util
        if self.net.params is None:
            self.net.init()
        # donated-buffer safety for the initial state too (a model fresh
        # from keras/dl4j import may hold numpy-aliased leaves). With a
        # process-wide GSPMD plan active (parallel/plan.use_mesh), the
        # laundering is sharding-aware: the owned copies land on the
        # plan placements and the net's compiled step compiles the
        # plan's collectives — the same zero-code-change pickup fit()
        # has.
        plan = None
        if self._uses_plan:
            from deeplearning4j_tpu.parallel.plan import active_plan
            plan = active_plan()
        if self._uses_plan and (plan is not None
                                or getattr(self.net, "_plan", None)
                                is not None):
            from deeplearning4j_tpu.nn.multilayer import _engage_plan_impl
            _engage_plan_impl(self.net, plan)
        else:
            self.net.params = param_util.own_tree(self.net.params)
            self.net.state = param_util.own_tree(self.net.state)
            self.net.opt_state = param_util.own_tree(self.net.opt_state)
        if getattr(self.net.conf, "backprop_type", None) == "tbptt":
            raise NotImplementedError(
                "ResilientTrainer does not support tbptt fits yet (chunk "
                "carries would have to be checkpointed mid-batch)")

    def finish(self):
        pass

    def plan_describe(self):
        """JSON descriptor of the active sharding plan (checkpoint
        extras), or None."""
        plan = getattr(self.net, "_plan", None)
        return None if plan is None else plan.describe()

    def post_restore(self):
        """Called after a checkpoint was restored into the net (the
        restored arrays live unsharded on the default device). Under an
        active plan, re-launder them onto the plan placements — the
        PR-3 own_tree contract, now sharding-aware — so a resumed step
        never donates misplaced (or heap-aliased) restored leaves."""
        if self._uses_plan and getattr(self.net, "_plan", None) is not None:
            from deeplearning4j_tpu.nn.multilayer import _engage_plan_impl
            _engage_plan_impl(self.net, self.net._plan)

    def make_source(self, data, batch_size):
        return self.net._as_iterator(data, batch_size)

    def batches(self, source):
        return iter(source)

    @staticmethod
    def reset(source):
        if hasattr(source, "reset"):
            source.reset()

    def epoch_key(self, epoch: int):
        return jax.random.PRNGKey(self.net.conf.seed
                                  + self.rng_mult * (epoch + 1))

    def snapshot(self):
        n = self.net
        return (_host_copy(n.params), _host_copy(n.opt_state),
                _host_copy(n.state))

    def restore(self, snap):
        from deeplearning4j_tpu.util.params import own_tree
        n = self.net
        # owned copies, NOT jnp.asarray: the snapshot's numpy buffers must
        # survive the restored params being donated into the retried step
        n.params = own_tree(snap[0])
        n.opt_state = own_tree(snap[1])
        n.state = own_tree(snap[2])

    def step(self, ds, sub):
        from deeplearning4j_tpu.nn.multilayer import _as_jnp
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        n = self.net
        fn = n._get_train_step(ds.features_mask, ds.labels_mask, None)
        xs = n._stage_x(ds.features)
        ys = _as_jnp(ds.labels, n._compute_dtype)
        fm = _as_jnp(ds.features_mask)
        lm = _as_jnp(ds.labels_mask)
        # under a GSPMD plan the batch shards over the mesh "data" axis
        # exactly like MultiLayerNetwork._fit_epoch (no-op without one)
        xs, ys, fm, lm = n._shard_batch(xs, ys, fm, lm)
        n.params, n.opt_state, n.state, loss, _ = fn(
            n.params, n.opt_state, n.state, xs, ys, fm, lm, sub, None)
        bs = int(np.shape(ds.features)[0])
        if xla_ledger.enabled():
            self._ledger_pending = (
                n._ledger_cache,
                (id(fn), xla_ledger.shape_key((xs, ys, fm, lm))),
                self.ledger_program, fn,
                (n.params, n.opt_state, n.state, xs, ys, fm, lm, sub,
                 None), bs)
        return loss, bs


class _GraphDriver(_NetDriver):
    """ComputationGraph per-call step (ComputationGraph._fit_epoch_per_call
    math; per-epoch RNG reseed for resumability)."""

    rng_mult = 331

    ledger_program = "graph/train_step"

    def make_source(self, data, batch_size):
        return data

    def batches(self, source):
        return self.net._iter_data(source)

    def step(self, mds, sub):
        from deeplearning4j_tpu.nn.multilayer import _as_jnp
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        n = self.net
        if n._train_step is None:
            n._train_step = n._make_train_step()
        inputs = n._shard_tuple(tuple(n._stage_x(f) for f in mds.features))
        labels = n._shard_tuple(tuple(_as_jnp(l, n._compute_dtype)
                                      for l in mds.labels))
        fmasks = n._shard_tuple(
            None if mds.features_masks is None else tuple(
                _as_jnp(m) for m in mds.features_masks))
        lmasks = n._shard_tuple(
            None if mds.labels_masks is None else tuple(
                _as_jnp(m) for m in mds.labels_masks))
        n.params, n.opt_state, n.state, loss, _ = n._train_step(
            n.params, n.opt_state, n.state, inputs, labels, fmasks,
            lmasks, sub, None)
        bs = int(np.shape(mds.features[0])[0])
        if xla_ledger.enabled():
            self._ledger_pending = (
                n._ledger_cache,
                (id(n._train_step), xla_ledger.shape_key(
                    (inputs, labels, fmasks, lmasks))),
                self.ledger_program, n._train_step,
                (n.params, n.opt_state, n.state, inputs, labels, fmasks,
                 lmasks, sub, None), bs)
        return loss, bs


class _WrapperDriver(_NetDriver):
    """ParallelWrapper SYNC_GRADIENTS step: the wrapper's compiled
    all-reduce step with its mesh-sharded batch placement."""

    rng_mult = 65537

    _uses_plan = False      # the wrapper manages its own plan/placement

    def __init__(self, wrapper):
        from deeplearning4j_tpu.parallel.wrapper import TrainingMode
        if wrapper.mode != TrainingMode.SYNC_GRADIENTS:
            raise NotImplementedError(
                "ResilientTrainer drives ParallelWrapper in SYNC_GRADIENTS "
                "mode only (AVERAGING keeps per-worker replica state that "
                "is not checkpointable step-by-step yet)")
        super().__init__(wrapper.model)
        self.wrapper = wrapper

    def prepare(self):
        super().prepare()
        w = self.wrapper
        if w._step_fn is None:
            w._step_fn = w._build_sync_step()
        if w._needs_placement():
            w._zero_place()
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS
        self._shard = NamedSharding(w.mesh, P(DATA_AXIS))

    def finish(self):
        if self.wrapper.zero_stage == 3:
            self.wrapper._zero_gather()

    def plan_describe(self):
        return self.wrapper.plan.describe()

    def post_restore(self):
        # restore_into left unsharded default-device arrays; re-establish
        # the plan layout or a stage-3/TP resume would run unsharded
        # (OOM on models that only fit sharded)
        if self.wrapper._needs_placement():
            self.wrapper._zero_place()

    def make_source(self, data, batch_size):
        if self.wrapper._is_graph:
            return data
        from deeplearning4j_tpu.data.iterator import DataSetIterator
        return data if isinstance(data, DataSetIterator) \
            else self.net._as_iterator(data, batch_size)

    def batches(self, source):
        return self.wrapper._batches(source)

    def step(self, batch, sub):
        w, n = self.wrapper, self.net
        x, y, fm, lm = batch
        bs = w._batch_count(x)
        x, y, fm, lm = w._device_batch(x, y, fm, lm, self._shard)
        n.params, n.opt_state, n.state, loss = w._step_fn(
            n.params, n.opt_state, n.state, x, y, fm, lm, sub)
        return loss, bs


# ------------------------------------------------------------------- trainer
class ResilientTrainer:
    """Fault-tolerant fit loop around MultiLayerNetwork / ComputationGraph
    / ParallelWrapper(SYNC_GRADIENTS).

    Usage:
        trainer = ResilientTrainer(net, "/ckpts", save_every_n_iterations=50)
        report = trainer.fit(iterator, epochs=10)     # auto-resumes

    `epochs` is the TOTAL target (unlike net.fit's "additional epochs"):
    a resumed run passes the same value and trains only the remainder.
    The trained model lives on the wrapped network; `fit` returns a
    FitReport describing what happened (resume source, skips, retries,
    preemption).

    Multi-host: only the coordinator process writes checkpoints (every
    process restores), override with `write_checkpoints=`.
    """

    def __init__(self, model, checkpoint_dir: str,
                 save_every_n_iterations: int = 50,
                 save_every_n_epochs: int = 1,
                 keep_last: int = 3,
                 policy: Optional[FaultPolicy] = None,
                 injector: Optional[FaultInjector] = None,
                 normalizer=None,
                 resume: bool = True,
                 write_checkpoints: Optional[bool] = None,
                 eval_gate=None):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        if isinstance(model, ParallelWrapper):
            self._driver = _WrapperDriver(model)
        else:
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            self._driver = _GraphDriver(model) \
                if isinstance(model, ComputationGraph) else _NetDriver(model)
        self.net = self._driver.net
        self.ckpt = CheckpointManager(checkpoint_dir, keep_last=keep_last)
        self.save_every_n_iterations = save_every_n_iterations
        self.save_every_n_epochs = save_every_n_epochs
        self.policy = policy or FaultPolicy()
        self.injector = injector if injector is not None \
            else FaultInjector.from_env()
        self.normalizer = normalizer
        self.resume = resume
        self.write_checkpoints = write_checkpoints
        # eval gate for continuous rollout: called after every checkpoint
        # save with the live network; return a metrics dict to bless the
        # checkpoint (CheckpointManager.bless -> blessed.json, which
        # serving/rollout.py tails) or None to withhold it from serving
        self.eval_gate = eval_gate
        self._jitter = random.Random(self.policy.seed)
        self._rng = None
        self._dispatch_idx = 0          # batches consumed, fit-global
        self._consecutive_skips = 0

    # ------------------------------------------------------------- plumbing
    def _writes_enabled(self) -> bool:
        if self.write_checkpoints is not None:
            return self.write_checkpoints
        try:
            from deeplearning4j_tpu.parallel.distributed import is_coordinator
            return is_coordinator()
        except Exception:
            return True

    def _normalizer_extra(self) -> Optional[dict]:
        nz = self.normalizer
        if nz is None or not hasattr(nz, "save"):
            return None
        import tempfile
        fd, path = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        try:
            nz.save(path)
            with open(path) as f:
                return json.load(f)
        except Exception as e:          # unfitted normalizer etc.
            log.warning("normalizer not checkpointed: %s", e)
            return None
        finally:
            try:
                os.remove(path)
            except OSError:
                pass

    def _restore_normalizer(self, blob: dict):
        from deeplearning4j_tpu.data import normalization
        kind = blob.get("kind")
        cls = getattr(normalization, kind, None)
        if cls is None or not hasattr(cls, "restore"):
            log.warning("checkpoint normalizer kind %r unknown; ignored",
                        kind)
            return None
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as tf:
            json.dump(blob, tf)
            path = tf.name
        try:
            return cls.restore(path)
        finally:
            try:
                os.remove(path)
            except OSError:
                pass

    def _save(self, report: FitReport, step_in_epoch: int):
        if not self._writes_enabled():
            return None
        extra = {
            "rng": np.asarray(self._rng).tolist(),
            "step_in_epoch": int(step_in_epoch),
            "dispatch_idx": int(self._dispatch_idx),
        }
        plan_desc = self._driver.plan_describe()
        if plan_desc is not None:
            # bank the GSPMD plan the run trained under, so a resume
            # onto a different mesh/zero_stage is detected and logged —
            # never silently misplaced (placements are re-derived by
            # post_restore either way)
            extra["plan"] = plan_desc
        src = getattr(self, "_source", None)
        src = src() if src is not None else None
        if src is not None and hasattr(src, "stream_state"):
            # the exact shard file/offset the next batch starts at —
            # step_in_epoch implies it (deterministic epoch order), but
            # the explicit position makes checkpoints auditable and
            # resumable by offset without replaying the order rule
            try:
                extra["stream"] = src.stream_state()
            except Exception:
                # checkpoint still lands (position replay covers resume)
                # but the missing offset must be visible in the log, not
                # silently absent from an "auditable" manifest
                log.warning("checkpoint: stream_state() unavailable — "
                            "banking position-replay resume only",
                            exc_info=True)
        if self.net._score is not None:
            extra["score"] = float(self.net._score)
        nz = self._normalizer_extra()
        if nz is not None:
            extra["normalizer"] = nz
        t0 = time.perf_counter()
        with monitor.span("resilience/checkpoint_save",
                          iteration=self.net.iteration_count):
            path = self.ckpt.save(self.net, extra)
        monitor.histogram("resilience_checkpoint_save_seconds",
                          "Checkpoint zip write + hash + manifest update"
                          ).observe(time.perf_counter() - t0)
        monitor.counter("resilience_checkpoints_written_total",
                        "Checkpoints written by ResilientTrainer").inc()
        report.checkpoints_written += 1
        log.info("checkpoint written: %s (iteration %d, epoch %d, step %d)",
                 path, self.net.iteration_count, self.net.epoch_count,
                 step_in_epoch)
        if self.eval_gate is not None:
            try:
                with monitor.span("resilience/eval_gate",
                                  iteration=self.net.iteration_count):
                    metrics = self.eval_gate(self.net)
            except Exception:           # noqa: BLE001 — a broken eval gate
                # must not kill training; it only withholds the blessing,
                # and loudly: an unblessed stream starves the rollout
                log.warning("eval gate raised; checkpoint NOT blessed",
                            exc_info=True)
                metrics = None
            if metrics is not None:
                if not isinstance(metrics, dict):
                    metrics = {"score": float(metrics)}
                self.ckpt.bless(path, metrics)
                report.checkpoints_blessed += 1
        return path

    # ------------------------------------------------------------ stepping
    def _run_step(self, batch, sub, step_idx: int, report: FitReport):
        """One guarded optimizer step. Returns (status, loss, batch_size)
        with status in {"applied", "skipped"}; raises _Unrecoverable when
        the consecutive-skip threshold trips."""
        policy = self.policy
        snap = self._driver.snapshot() if policy.guards_steps else None
        attempt = 0
        while True:
            # per-attempt clock: train_step_seconds and the train/step
            # span must time ONLY the attempt that landed — backoff
            # sleeps and failed attempts would otherwise make retried
            # steps read as slow compute
            attempt_start = time.perf_counter()
            try:
                if self.injector is not None:
                    self.injector.before_step(step_idx)
                loss, bs = self._driver.step(batch, sub)
                wait_start = time.perf_counter()
                # block for device completion FIRST (goodput:
                # step_compute; banks per-shard barrier wait under a
                # plan), so host_sync covers only the narrow D2H fetch
                goodput.device_wait(loss)
                fetch_start = time.perf_counter()
                monitor.add_span("train/device_wait", wait_start,
                                 fetch_start)
                loss_f = float(loss)
                step_end = time.perf_counter()
                step_secs = step_end - attempt_start
                monitor.add_span("train/host_sync", fetch_start, step_end)
                monitor.add_span("train/step", attempt_start,
                                 step_end, step=step_idx)
                # capture AFTER the attempt clock stops: the first sight
                # of a program pays an AOT lower+compile that must not
                # read as compute time
                self._driver.capture_ledger()
                break
            except policy.transient_errors as e:
                attempt += 1
                monitor.counter("resilience_retries_total",
                                "Transient-error step retries").inc()
                monitor.add_span("resilience/step_retry", attempt_start,
                                 time.perf_counter(), step=step_idx,
                                 attempt=attempt, error=str(e))
                if snap is not None:
                    self._driver.restore(snap)
                if attempt > policy.max_retries:
                    log.error("step %d failed after %d retries: %s — "
                              "checkpointing last good state and raising",
                              step_idx, policy.max_retries, e)
                    raise
                delay = min(policy.backoff_base * (2 ** (attempt - 1)),
                            policy.backoff_max)
                delay *= 0.5 + self._jitter.random()     # jitter in [.5, 1.5)
                log.warning("transient error at step %d (attempt %d/%d): "
                            "%s — retrying in %.3fs", step_idx, attempt,
                            policy.max_retries, e, delay)
                report.retries += 1
                time.sleep(delay)
        if self.injector is not None:
            loss_f = self.injector.corrupt_loss(step_idx, loss_f)
        if not math.isfinite(loss_f) and policy.skip_nonfinite:
            if snap is not None:
                self._driver.restore(snap)
            self._consecutive_skips += 1
            report.skipped_steps += 1
            monitor.counter("resilience_steps_skipped_total",
                            "Steps skipped on non-finite loss").inc()
            monitor.instant("resilience/nan_skip", step=step_idx)
            log.warning("non-finite loss %s at step %d: skipping batch "
                        "(%d consecutive skips, threshold %d)", loss_f,
                        step_idx, self._consecutive_skips,
                        policy.max_consecutive_skips)
            if self._consecutive_skips > policy.max_consecutive_skips:
                raise _Unrecoverable(
                    f"{self._consecutive_skips} consecutive non-finite "
                    f"steps (threshold {policy.max_consecutive_skips}) "
                    f"at step {step_idx}")
            return "skipped", loss_f, bs
        self._consecutive_skips = 0
        from deeplearning4j_tpu.nn.multilayer import _record_iteration
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        _record_iteration(loss_f, bs, step_seconds=step_secs)
        if xla_ledger.enabled() and not self._driver._ledger_fresh:
            # feed the MFU accountant the attempt-that-landed wall time
            # against the program the driver captured for this step; the
            # debut step (fresh capture) is skipped — its wall time
            # includes the jit compile
            xla_ledger.observe_step(self._driver._ledger_rec, step_secs)
        return "applied", loss_f, bs

    # ------------------------------------------------------------------ fit
    def fit(self, data, epochs: int = 1, batch_size: int = 32) -> FitReport:
        report = FitReport()
        # the goodput session owns the WHOLE resilient fit wall-clock —
        # prepare, restore, replay, every epoch, the final save — so the
        # report's categories sum to what an outside stopwatch measures
        # (the exclusivity contract telemetry_smoke enforces)
        gp_session = goodput.fit_begin("resilient/fit")
        try:
            return self._fit_guarded(data, epochs, batch_size, report)
        finally:
            gp = goodput.fit_end(gp_session)
            if gp is not None:
                report.goodput_pct = gp["goodput_pct"]
                report.time_by_category = gp["categories"]

    def _fit_guarded(self, data, epochs: int, batch_size: int,
                     report: FitReport) -> FitReport:
        net = self.net
        policy = self.policy
        self._driver.prepare()

        # -------- auto-resume from the newest valid checkpoint
        step_in_epoch = 0
        resumed_mid_epoch = False
        if self.resume:
            entry = self.ckpt.latest_valid()
            if entry is not None:
                t0 = time.perf_counter()
                with monitor.span("resilience/checkpoint_restore",
                                  path=entry["path"]):
                    extra = self.ckpt.restore_into(net, entry["path"])
                monitor.histogram("resilience_checkpoint_restore_seconds",
                                  "Checkpoint verify + load into the model"
                                  ).observe(time.perf_counter() - t0)
                monitor.counter("resilience_resumes_total",
                                "Auto-resumes from a checkpoint").inc()
                report.resumed_from = entry["path"]
                step_in_epoch = int(extra.get("step_in_epoch", 0))
                self._dispatch_idx = int(extra.get("dispatch_idx", 0))
                if step_in_epoch > 0 and "rng" in extra:
                    self._rng = jnp.asarray(
                        np.asarray(extra["rng"], dtype=np.uint32))
                    resumed_mid_epoch = True
                if "score" in extra:
                    net._score = float(extra["score"])
                if "normalizer" in extra and self.normalizer is None:
                    self.normalizer = self._restore_normalizer(
                        extra["normalizer"])
                live_plan = self._driver.plan_describe()
                if extra.get("plan") != live_plan:
                    # resuming onto a different mesh layout is SUPPORTED
                    # (checkpoints store whole host arrays; post_restore
                    # re-launders them onto the live plan's placements)
                    # but must be loud — a silent layout change is how
                    # misplaced-restore bugs ship
                    log.warning(
                        "resuming onto a different sharding plan: "
                        "checkpoint trained under %s, live plan is %s — "
                        "placements re-derived from the live plan",
                        extra.get("plan"), live_plan)
                self._driver.post_restore()
                log.info("resumed from %s (iteration %d, epoch %d, "
                         "step-in-epoch %d)", entry["path"],
                         net.iteration_count, net.epoch_count, step_in_epoch)

        source = self._driver.make_source(data, batch_size)
        # weakly held: _save banks the seekable stream position while the
        # local `source` keeps it alive for the fit; a strong ref would
        # pin a multi-process ETL pipeline (workers + shared-memory ring)
        # to the trainer's lifetime after fit() returns
        try:
            self._source = weakref.ref(source)
        except TypeError:
            self._source = None     # plain list/array: no stream_state
        if self.normalizer is not None \
                and getattr(source, "pre_processor", False) is None \
                and hasattr(source, "set_pre_processor"):
            source.set_pre_processor(self.normalizer)

        if any(getattr(lst, "wants_gradients", False)
               for lst in net.listeners):
            log.warning("gradient-capturing listeners (wants_gradients) are "
                        "not fed by the resilient fit loop — gradient/update "
                        "capture will be empty; use the plain fit() for "
                        "capture runs")

        div_guard = None
        if policy.explosion_factor:
            def _diverged(model, iteration, msg):
                raise TrainingDivergedError(msg)
            div_guard = DivergenceListener(
                explosion_factor=policy.explosion_factor,
                window=policy.explosion_window, on_divergence=_diverged)

        steps_since_save = 0
        rng_at_step_start = None    # pre-split carry of the in-flight step
        with PreemptionGuard() as guard, \
                monitor.span("resilience/fit", epochs=epochs):
            # the uninterrupted run resets the source once per completed
            # epoch — replay those resets so epoch-dependent shuffles
            # match. A LIVE streaming source re-fit in the same process
            # (preempt -> fit again on the same pipeline) already
            # consumed its in-fit resets; stream_state names its current
            # epoch, so replay only the difference — blind replay would
            # double-advance the shuffle permutation the seek below
            # resumes into.
            src_epoch = 0
            state_fn = getattr(source, "stream_state", None)
            if callable(state_fn):
                src_epoch = int(state_fn().get("epoch") or 0)
            for _ in range(max(0, net.epoch_count - src_epoch)):
                self._driver.reset(source)
            try:
                while net.epoch_count < epochs:
                    epoch = net.epoch_count
                    if not resumed_mid_epoch:
                        self._rng = self._driver.epoch_key(epoch)
                        step_in_epoch = 0
                        for lst in net.listeners:
                            lst.on_epoch_start(net, epoch)
                    resumed_mid_epoch = False
                    consumed = 0
                    if step_in_epoch > 0 \
                            and getattr(source, "supports_seek", False):
                        # streaming sources (ShardDataSetIterator) land on
                        # the exact next shard offset instead of replaying
                        # — decoding the whole stream prefix just to throw
                        # it away is the resume tax this skips
                        seek_start = time.perf_counter()
                        source.seek(step_in_epoch)
                        consumed = step_in_epoch
                        monitor.add_span("train/resume_replay", seek_start,
                                         time.perf_counter(),
                                         seeked=step_in_epoch)
                        if hasattr(source, "stream_state"):
                            log.info("resume: seeked stream to %s",
                                     source.stream_state())
                    it = self._driver.batches(source)
                    while True:
                        if guard.requested or (
                                self.injector is not None
                                and self.injector.should_preempt(
                                    self._dispatch_idx)):
                            self._save(report, step_in_epoch)
                            report.preempted = True
                            report.final_score = net._score
                            monitor.counter(
                                "resilience_preemptions_total",
                                "Preemption-triggered clean stops").inc()
                            monitor.instant("resilience/preempted",
                                            iteration=net.iteration_count)
                            log.warning("preempted: checkpointed at "
                                        "iteration %d; re-run to resume",
                                        net.iteration_count)
                            return report
                        etl_start = time.perf_counter()
                        if self.injector is not None:
                            # inside the ETL window: an injected stall
                            # must read as data_wait, like a real one
                            self.injector.before_fetch(self._dispatch_idx)
                        try:
                            batch = next(it)
                        except StopIteration:
                            break
                        etl_end = time.perf_counter()
                        if consumed < step_in_epoch:    # resume fast-forward
                            consumed += 1
                            # replayed batches are resume overhead, not
                            # data_wait: the goodput ledger bills them to
                            # resume_replay
                            monitor.add_span("train/resume_replay",
                                             etl_start, etl_end,
                                             step=consumed)
                            continue
                        consumed += 1
                        etl_ms = (etl_end - etl_start) * 1e3
                        monitor.add_span("train/etl", etl_start, etl_end,
                                         step=self._dispatch_idx)
                        rng_at_step_start = self._rng
                        self._rng, sub = jax.random.split(self._rng)
                        step_idx = self._dispatch_idx
                        self._dispatch_idx += 1
                        status, loss_f, bs = self._run_step(
                            batch, sub, step_idx, report)
                        rng_at_step_start = None    # step landed: no rewind
                        step_in_epoch = consumed
                        if status == "skipped":
                            continue
                        net._score = loss_f
                        report.applied_steps += 1
                        for lst in net.listeners:
                            lst.iteration_done(net, net.iteration_count,
                                               epoch, loss_f, etl_ms, bs)
                        if div_guard is not None:
                            div_guard.iteration_done(net,
                                                     net.iteration_count,
                                                     epoch, loss_f, 0.0, bs)
                        net.iteration_count += 1
                        steps_since_save += 1
                        if self.save_every_n_iterations and \
                                steps_since_save >= \
                                self.save_every_n_iterations:
                            self._save(report, step_in_epoch)
                            steps_since_save = 0
                    for lst in net.listeners:
                        lst.on_epoch_end(net, epoch)
                    net.epoch_count += 1
                    self._driver.reset(source)
                    step_in_epoch = 0
                    if self.save_every_n_epochs and \
                            net.epoch_count % self.save_every_n_epochs == 0 \
                            and net.epoch_count < epochs:
                        self._rng = self._driver.epoch_key(net.epoch_count)
                        self._save(report, 0)
                        steps_since_save = 0
            except (_Unrecoverable, TrainingDivergedError) as e:
                return self._handle_unrecoverable(report, str(e))
            except policy.transient_errors:
                # retries exhausted: state is at the last good step —
                # checkpoint it so the operator can resume, then surface
                # the original error (a failing emergency save must not
                # mask it). The RNG carry was already split for the failed
                # step while step_in_epoch was not advanced — rewind it so
                # the resumed run re-derives the SAME subkey for that step
                # (bitwise resume parity holds across the failure)
                if rng_at_step_start is not None:
                    self._rng = rng_at_step_start
                    self._dispatch_idx = max(0, self._dispatch_idx - 1)
                try:
                    self._save(report, step_in_epoch)
                except Exception as save_err:
                    log.error("emergency checkpoint failed: %s", save_err)
                raise
            self._driver.finish()
            # final checkpoint: a re-run of the same command sees
            # epoch_count == epochs and returns without retraining. A
            # no-op rerun (resumed, nothing trained) must NOT save again —
            # duplicate finals would rotate real history out of keep_last.
            if report.applied_steps > 0 or report.resumed_from is None:
                self._rng = self._driver.epoch_key(net.epoch_count)
                self._save(report, 0)
        report.final_score = net._score
        return report

    def _handle_unrecoverable(self, report: FitReport, reason: str):
        """Graceful degradation: restore the newest good checkpoint so the
        model is left usable, then stop (or raise, per policy)."""
        report.diverged = True
        monitor.counter("resilience_divergence_rollbacks_total",
                        "Unrecoverable divergences rolled back to the "
                        "last good checkpoint").inc()
        entry = self.ckpt.latest_valid()
        if entry is not None:
            self.ckpt.restore_into(self.net, entry["path"])
            self._driver.post_restore()
            report.restored_checkpoint = entry["path"]
            log.error("unrecoverable divergence (%s); restored last good "
                      "checkpoint %s", reason, entry["path"])
        else:
            log.error("unrecoverable divergence (%s) and no valid "
                      "checkpoint to restore", reason)
        report.final_score = self.net._score
        if self.policy.on_unrecoverable == "raise":
            raise TrainingDivergedError(
                f"{reason}; model restored to "
                f"{entry['path'] if entry else 'initial state'}")
        return report
