"""Training listeners — the observability callback bus.

Parity with DL4J's TrainingListener/IterationListener framework
(deeplearning4j-nn/.../optimize/api/ + optimize/listeners/):
- ScoreIterationListener          (prints score every N iterations)
- PerformanceListener             (samples/sec, batches/sec, ETL time;
                                   PerformanceListener.java:22-87)
- CollectScoresIterationListener  (score history collection)
- TimeIterationListener           (ETA logging)
- EvaluativeListener              (periodic held-out evaluation)
- CheckpointListener              (periodic checkpoints w/ keepLast(n);
                                   checkpoint/CheckpointListener.java:72-144)
"""
from __future__ import annotations

import logging
import os
import re
import time
from typing import Callable, List, Optional

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    #: True for listeners whose iteration_done inspects the MODEL (params,
    #: opt state) rather than just the scalar score stream. The
    #: input-pipelined fit path (fit(scan_steps=K)) delivers iteration_done
    #: up to 2K-1 steps after the params have advanced, so such listeners
    #: force a fallback to the per-call path where model state and
    #: iteration number are always in sync.
    reads_model = False

    def on_epoch_start(self, model, epoch: int):
        pass

    def on_epoch_end(self, model, epoch: int):
        pass

    def iteration_done(self, model, iteration: int, epoch: int,
                       score: float, etl_ms: float = 0.0,
                       batch_size: int = 0):
        pass


class ScoreIterationListener(TrainingListener):
    def __init__(self, print_iterations: int = 10):
        self.n = max(int(print_iterations), 1)

    def iteration_done(self, model, iteration, epoch, score, etl_ms=0.0,
                       batch_size=0):
        if iteration % self.n == 0:
            log.info("Score at iteration %d is %s", iteration, score)


class PerformanceListener(TrainingListener):
    """Reports throughput per iteration (DL4J PerformanceListener.java:22-87).

    Every reported record carries the SAME four numbers in the history
    dict, the log line, and the telemetry registry (monitor/metrics.py:
    train_examples_per_sec / train_batches_per_sec gauges and the
    train_etl_seconds histogram) — one source of truth for throughput,
    whether you read logs, listener history, or a /metrics scrape."""

    def __init__(self, frequency: int = 1, report: bool = True):
        self.frequency = max(int(frequency), 1)
        self.report = report
        self._last_time: Optional[float] = None
        self._compiled_logged: set = set()   # ledger fingerprints reported
        self.history: List[dict] = []

    def _report_compiled(self):
        """Once per distinct compiled program (first iteration after its
        compile): log HBM peak and MFU, sourced from the monitor.xla
        ledger — no re-lowering, just a dict read. No-op while the ledger
        is disabled."""
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        if not xla_ledger.enabled():
            return
        rec = xla_ledger.latest_record("train")
        if rec is None or rec.fingerprint in self._compiled_logged:
            return
        mfu = xla_ledger.last_mfu("train")
        if mfu is None and rec.flops and xla_ledger.device_peak_flops():
            # debut iteration: its wall time included the compile, so no
            # MFU sample exists yet — log on the next (steady) iteration
            return
        self._compiled_logged.add(rec.fingerprint)
        peak = rec.hbm_peak_bytes
        log.info(
            "compiled step %s (fingerprint %s): %s GFLOP/call, HBM peak "
            "%s, compile %.2f s, mfu %s",
            rec.name, rec.fingerprint,
            "n/a" if not rec.flops else f"{rec.flops / 1e9:.2f}",
            "n/a" if peak is None else f"{peak / 2**20:.1f} MiB",
            rec.compile_seconds,
            "n/a" if mfu is None else f"{mfu:.1f}%")

    def iteration_done(self, model, iteration, epoch, score, etl_ms=0.0,
                       batch_size=0):
        from deeplearning4j_tpu import monitor
        if self.report:
            self._report_compiled()
        now = time.perf_counter()
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            rec = {
                "iteration": iteration,
                "batches_per_sec": 1.0 / dt if dt > 0 else float("inf"),
                "examples_per_sec": batch_size / dt if dt > 0 else float("inf"),
                "etl_ms": etl_ms,
                "iteration_ms": dt * 1e3,
            }
            # historical key kept so existing consumers don't break
            rec["samples_per_sec"] = rec["examples_per_sec"]
            self.history.append(rec)
            if dt > 0:
                monitor.gauge("train_examples_per_sec",
                              "Training throughput, examples/sec "
                              "(PerformanceListener)").set(
                    rec["examples_per_sec"])
                monitor.gauge("train_batches_per_sec",
                              "Training throughput, batches/sec "
                              "(PerformanceListener)").set(
                    rec["batches_per_sec"])
            monitor.histogram("train_etl_seconds",
                              "Host ETL time per reported iteration "
                              "(PerformanceListener)").observe(etl_ms / 1e3)
            # goodput beside throughput, sourced from the ledger's live
            # session (the same accumulators /metrics scrapes, so the
            # log line and the gauge cannot disagree); absent while the
            # ledger is off
            from deeplearning4j_tpu.monitor import goodput
            gp = goodput.live_stats()
            if gp is not None:
                rec["goodput_pct"] = gp["goodput_pct"]
                rec["dominant_stall"] = gp["dominant_stall"]
            if self.report:
                suffix = ""
                if gp is not None:
                    suffix = (f"; goodput: {gp['goodput_pct']:.1f}%% "
                              f"(top stall: {gp['dominant_stall']})")
                log.info("ETL: %.0f ms; iteration %d; iteration time: %.1f ms; "
                         "examples/sec: %.1f; batches/sec: %.2f" + suffix,
                         etl_ms, iteration, rec["iteration_ms"],
                         rec["examples_per_sec"], rec["batches_per_sec"])
        self._last_time = now


class CollectScoresIterationListener(TrainingListener):
    def __init__(self, frequency: int = 1):
        self.frequency = max(int(frequency), 1)
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch, score, etl_ms=0.0,
                       batch_size=0):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(score)))


class TimeIterationListener(TrainingListener):
    """Logs remaining-time estimate (DL4J TimeIterationListener)."""

    def __init__(self, total_iterations: int, frequency: int = 50):
        self.total = total_iterations
        self.frequency = max(int(frequency), 1)
        self._start: Optional[float] = None

    def iteration_done(self, model, iteration, epoch, score, etl_ms=0.0,
                       batch_size=0):
        if self._start is None:
            self._start = time.perf_counter()
            return
        if iteration % self.frequency == 0 and iteration > 0:
            elapsed = time.perf_counter() - self._start
            rate = elapsed / iteration
            remaining = (self.total - iteration) * rate
            log.info("Remaining time estimate: %.1f s (iteration %d/%d)",
                     remaining, iteration, self.total)


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (DL4J EvaluativeListener)."""

    reads_model = True

    def __init__(self, iterator, frequency: int = 1, unit: str = "epoch"):
        self.iterator = iterator
        self.frequency = max(int(frequency), 1)
        self.unit = unit
        self.results: List[tuple] = []

    def iteration_done(self, model, iteration, epoch, score, etl_ms=0.0,
                       batch_size=0):
        if self.unit == "iteration" and iteration % self.frequency == 0:
            self._evaluate(model, iteration)

    def on_epoch_end(self, model, epoch):
        if self.unit == "epoch" and (epoch + 1) % self.frequency == 0:
            self._evaluate(model, epoch)

    def _evaluate(self, model, at):
        ev = model.evaluate(self.iterator)
        self.results.append((at, ev))
        log.info("Evaluation at %s %d: accuracy=%.4f", self.unit, at, ev.accuracy())


class CheckpointListener(TrainingListener):
    """Periodic checkpoint saver with retention policy
    (DL4J checkpoint/CheckpointListener.java:46-144: saveEveryNIterations /
    saveEveryNEpochs + keepLast).

    `async_save=True` moves the zip serialization off the training thread
    (the device array snapshot is taken synchronously — params are copied
    to host before the step loop continues mutating them — but compression
    and file IO happen in a background worker, so checkpointing does not
    stall the accelerator). Call `flush()` (or let the listener be used as
    a context manager) to wait for pending saves; errors from background
    saves surface on the next save or flush."""

    reads_model = True      # snapshots params: scan-mode fit falls back

    def __init__(self, directory: str, save_every_n_iterations: Optional[int] = None,
                 save_every_n_epochs: Optional[int] = None, keep_last: int = 3,
                 async_save: bool = False):
        self.dir = directory
        self.every_iter = save_every_n_iterations
        self.every_epoch = save_every_n_epochs
        self.keep_last = keep_last
        self.async_save = async_save
        self._saved: List[str] = []
        self._executor = None
        self._pending: List = []
        os.makedirs(directory, exist_ok=True)

    def _prune(self):
        """keep_last retention by directory scan: only files matching the
        tag kinds THIS listener writes (checkpoint_iter_* and/or
        checkpoint_epoch_*) count and get deleted — foreign files in the
        checkpoint directory (exports, notes, resilience manifests, a
        sibling listener's other-kind checkpoints) are ignored. Scanning
        (vs. an in-memory list) also retires leftovers from a previous
        run of the same job."""
        kinds = [k for k, on in (("iter", self.every_iter),
                                 ("epoch", self.every_epoch)) if on]
        if not kinds:
            return
        pat = re.compile(rf"^checkpoint_({'|'.join(kinds)})_(\d+)\.zip$")
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        # order by the monotone counter in the filename, NOT mtime —
        # coarse-granularity or copied-file mtimes would make ties
        # arbitrary and could delete the newest checkpoint. Iteration and
        # epoch counters are not comparable to each other, so retention
        # applies per kind (keep_last of each).
        for kind in kinds:
            own = sorted((int(m.group(2)), n) for n in names
                         for m in [pat.match(n)] if m and m.group(1) == kind)
            while len(own) > self.keep_last:
                try:
                    os.remove(os.path.join(self.dir, own.pop(0)[1]))
                except OSError:
                    pass

    def _save(self, model, tag: str):
        # save_model's default atomic mode (tmp + os.replace) means a kill
        # mid-save can never leave a truncated checkpoint zip at `path`
        from deeplearning4j_tpu.util.serialization import save_model
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        if self.async_save:
            import concurrent.futures

            import numpy as np

            import jax
            if self._executor is None:
                self._executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ckpt")
            self._raise_pending_errors(block=False)
            # host snapshot NOW: copy() materializes independent device
            # buffers (the live ones are donated by the next step), the
            # counters ride along, and the optimizer state gets its own
            # forced host copies (np.asarray could alias the soon-donated
            # originals on CPU backends)
            snap = model.copy()
            snap.iteration_count = model.iteration_count
            snap.epoch_count = model.epoch_count
            snap.params = jax.tree_util.tree_map(np.asarray, snap.params)
            snap.state = jax.tree_util.tree_map(np.asarray, snap.state)
            snap.opt_state = jax.tree_util.tree_map(
                lambda a: np.array(a, copy=True), model.opt_state)

            def job():
                save_model(snap, path)
                # retention runs AFTER the file lands; the single-worker
                # executor serializes these mutations
                self._saved.append(path)
                self._prune()

            self._pending.append(self._executor.submit(job))
        else:
            save_model(model, path)
            self._saved.append(path)
            self._prune()

    def _raise_pending_errors(self, block: bool):
        still = []
        for f in self._pending:
            if f.done() or block:
                f.result()          # re-raises background failures
            else:
                still.append(f)
        self._pending = still

    def flush(self):
        """Block until all background saves land (async_save mode)."""
        self._raise_pending_errors(block=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        try:
            self.flush()
        finally:                    # never leak the worker thread
            if self._executor is not None:
                self._executor.shutdown(wait=True)

    def iteration_done(self, model, iteration, epoch, score, etl_ms=0.0,
                       batch_size=0):
        if self.every_iter and iteration > 0 and iteration % self.every_iter == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model, epoch):
        if self.every_epoch and (epoch + 1) % self.every_epoch == 0:
            self._save(model, f"epoch_{epoch}")


class ProfilerListener(TrainingListener):
    """Captures an XLA device trace with jax.profiler for a window of
    iterations (SURVEY.md §5.1: the reference's op-level profiling lives in
    external ND4J; the TPU equivalent is the XLA profiler, exposed here as
    an ordinary listener).

    Usage:
        net.set_listeners(ProfilerListener("/tmp/trace", start_iteration=5,
                                           num_iterations=3))
        net.fit(...)          # iterations [5, 8) are traced
        # inspect with tensorboard or xprof on the written trace dir
    """

    reads_model = True      # brackets live device work: needs per-call fit

    def __init__(self, log_dir: str, start_iteration: int = 5,
                 num_iterations: int = 3):
        self.log_dir = log_dir
        self.start_iteration = start_iteration
        self.stop_iteration = start_iteration + num_iterations
        self._active = False
        self.trace_dir: Optional[str] = None

    def iteration_done(self, model, iteration, epoch, score, etl_ms,
                       batch_size):
        import jax
        if iteration + 1 == self.start_iteration and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif iteration + 1 >= self.stop_iteration and self._active:
            jax.profiler.stop_trace()
            self._active = False
            self.trace_dir = self.log_dir
            log.info("profiler trace written to %s", self.log_dir)

    def on_epoch_end(self, model, epoch):
        if self._active:        # epoch ended inside the window: close out
            import jax
            jax.profiler.stop_trace()
            self._active = False
            self.trace_dir = self.log_dir


class DivergenceListener(TrainingListener):
    """Training failure detection (SURVEY.md §5.2/5.3: the reference has no
    in-tree sanitizer; its closest analog is cuDNN helpers counting
    failures). Watches the score stream for NaN/Inf or a sustained
    explosion and either raises TrainingDivergedError (default — fail the
    job before it burns more TPU hours) or invokes a callback (alerting /
    checkpoint-and-restart policies).

    Usage:
        net.set_listeners(DivergenceListener())                  # raise
        net.set_listeners(DivergenceListener(on_divergence=cb))  # custom
    """

    def __init__(self, explosion_factor: float = 1e4,
                 window: int = 20, on_divergence: Optional[Callable] = None):
        self.explosion_factor = explosion_factor
        self.window = window
        self.on_divergence = on_divergence
        # a custom callback receives the model; the default raise path only
        # reads the score stream and stays scan-compatible
        self.reads_model = on_divergence is not None
        self._recent: List[float] = []

    def iteration_done(self, model, iteration, epoch, score, etl_ms,
                       batch_size):
        import math
        bad = None
        if not math.isfinite(score):
            bad = f"non-finite score {score} at iteration {iteration}"
        else:
            self._recent.append(score)
            if len(self._recent) > self.window:
                self._recent.pop(0)
            baseline = min(self._recent)
            if baseline > 0 and score > baseline * self.explosion_factor:
                bad = (f"score exploded: {score:.4g} > "
                       f"{self.explosion_factor:g} x recent best "
                       f"{baseline:.4g} at iteration {iteration}")
        if bad:
            if self.on_divergence is not None:
                self.on_divergence(model, iteration, bad)
            else:
                raise TrainingDivergedError(bad)


class TrainingDivergedError(RuntimeError):
    """Raised by DivergenceListener when the loss goes NaN/Inf/explodes."""
