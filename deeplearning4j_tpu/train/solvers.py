"""Full-batch optimizers: line search, conjugate gradient, L-BFGS.

Parity targets: DL4J `optimize/solvers/` —
`BackTrackLineSearch.java:64` (Armijo backtracking with ALF=1e-4 sufficient
decrease and a step cap), `LineGradientDescent.java` (steepest descent +
line search), `ConjugateGradient.java:40` (Polak-Ribiere gamma = max(dgg/gg,
0) with automatic restart), `LBFGS.java:39` (two-loop recursion over an
m-deep history).

TPU-native stance: the loss/gradient of the FULL batch is one jitted XLA
program over the flat parameter vector (the flattenedParams view — whole-
model vector ops are exactly what these solvers need); the line-search /
direction logic is data-dependent host control flow, which is where it
belongs. One device round-trip per function evaluation.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.util import params as param_util


def _flat_loss_fn(net, x, y):
    """loss(flat_params) for the full batch, jitted once per solver run."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    template = net.params
    state = net.state
    is_graph = isinstance(net, ComputationGraph)

    @jax.jit
    def f(flat):
        p = param_util.flat_to_params(flat, template)
        if is_graph:
            loss, _ = net._score_fn(p, state, (x,), (y,), None, None,
                                    False, None)
        else:
            loss, _ = net._score_fn(p, state, x, y, None, None, False, None)
        return loss

    return jax.jit(jax.value_and_grad(f))


class BackTrackLineSearch:
    """Armijo backtracking along a search direction
    (BackTrackLineSearch.java:64 semantics: sufficient-decrease constant
    ALF=1e-4, step-norm cap, geometric backtracking)."""

    ALF = 1e-4

    def __init__(self, value_and_grad: Callable, max_iterations: int = 5,
                 step_max: float = 100.0):
        self.value_and_grad = value_and_grad
        self.max_iterations = max_iterations
        self.step_max = step_max

    def optimize(self, flat, f0, g0, direction) -> Tuple[float, jnp.ndarray, float]:
        """Returns (step, new_flat, new_loss). direction is a DESCENT
        direction (the step moves along +direction)."""
        slope = float(jnp.vdot(g0, direction))
        if slope >= 0:           # not a descent direction: fall back
            direction = -g0
            slope = float(jnp.vdot(g0, direction))
            if slope >= 0:       # zero gradient
                return 0.0, flat, float(f0)
        dnorm = float(jnp.linalg.norm(direction))
        if dnorm > self.step_max:
            direction = direction * (self.step_max / dnorm)
            slope *= self.step_max / dnorm
        step = 1.0
        best = (0.0, flat, float(f0))
        for _ in range(self.max_iterations):
            cand = flat + step * direction
            f_new, _ = self.value_and_grad(cand)
            f_new = float(f_new)
            if np.isfinite(f_new) and \
                    f_new <= float(f0) + self.ALF * step * slope:
                return step, cand, f_new
            if np.isfinite(f_new) and f_new < best[2]:
                best = (step, cand, f_new)
            step *= 0.5
        return best


@dataclasses.dataclass
class _SolverResult:
    scores: List[float]
    iterations: int

    @property
    def final_score(self) -> float:
        return self.scores[-1]


class _FullBatchSolver:
    """Shared driver: build the jitted full-batch value_and_grad, iterate
    directions + line searches until tolerance/max_iterations."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6,
                 max_line_search_iterations: int = 8):
        self.max_iterations = max_iterations
        self.tolerance = tolerance
        self.max_line_search_iterations = max_line_search_iterations

    def _direction(self, g, state: dict) -> jnp.ndarray:
        raise NotImplementedError

    def optimize(self, net, data) -> _SolverResult:
        from deeplearning4j_tpu.data.dataset import DataSet
        if isinstance(data, tuple):
            x, y = data
        elif isinstance(data, DataSet):
            x, y = data.features, data.labels
        else:
            raise ValueError("solver needs (features, labels) or a DataSet")
        x = jnp.asarray(np.asarray(x), net._compute_dtype)
        y = jnp.asarray(np.asarray(y), net._compute_dtype)
        vg = _flat_loss_fn(net, x, y)
        flat = param_util.params_to_flat(net.params)
        ls = BackTrackLineSearch(vg, self.max_line_search_iterations)
        state: dict = {}
        scores = []
        f0, g = vg(flat)
        scores.append(float(f0))
        it = 0
        for it in range(1, self.max_iterations + 1):
            direction = self._direction(g, state)
            step, flat, f_new = ls.optimize(flat, f0, g, direction)
            if step == 0.0:
                break
            f_prev = float(f0)
            f0, g = vg(flat)
            scores.append(float(f0))
            state["last_step"] = step
            if abs(f_prev - float(f0)) < self.tolerance * max(1.0, abs(f_prev)):
                break
        net.set_params_flat(flat)
        net._score = scores[-1]
        return _SolverResult(scores=scores, iterations=it)


class LineGradientDescent(_FullBatchSolver):
    """Steepest descent + backtracking line search
    (LineGradientDescent.java)."""

    def _direction(self, g, state):
        return -g


class ConjugateGradient(_FullBatchSolver):
    """Nonlinear CG, Polak-Ribiere with max(gamma, 0) restart
    (ConjugateGradient.java:40,73-77)."""

    def _direction(self, g, state):
        g_last = state.get("g_last")
        d_last = state.get("d_last")
        if g_last is None:
            d = -g
        else:
            gg = float(jnp.vdot(g_last, g_last))
            dgg = float(jnp.vdot(g - g_last, g))
            gamma = max(dgg / max(gg, 1e-12), 0.0)   # gamma=0 -> restart
            d = -g + gamma * d_last
        state["g_last"] = g
        state["d_last"] = d
        return d


class LBFGS(_FullBatchSolver):
    """Limited-memory BFGS via the two-loop recursion (LBFGS.java:39);
    history depth m=10 like the reference default."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-6,
                 max_line_search_iterations: int = 8, m: int = 10):
        super().__init__(max_iterations, tolerance,
                         max_line_search_iterations)
        self.m = m

    def _direction(self, g, state):
        s_hist: List = state.setdefault("s", [])
        y_hist: List = state.setdefault("y", [])
        if "g_last" in state and "x_delta" in state:
            s = state["x_delta"]
            yv = g - state["g_last"]
            sy = float(jnp.vdot(s, yv))
            if sy > 1e-10:          # curvature condition
                s_hist.append(s)
                y_hist.append(yv)
                if len(s_hist) > self.m:
                    s_hist.pop(0)
                    y_hist.pop(0)
        q = g
        alphas = []
        for s, yv in zip(reversed(s_hist), reversed(y_hist)):
            rho = 1.0 / float(jnp.vdot(yv, s))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho, s, yv))
            q = q - a * yv
        if y_hist:
            s, yv = s_hist[-1], y_hist[-1]
            q = q * (float(jnp.vdot(s, yv)) / float(jnp.vdot(yv, yv)))
        for a, rho, s, yv in reversed(alphas):
            b = rho * float(jnp.vdot(yv, q))
            q = q + (a - b) * s
        state["g_last"] = g
        return -q

    def optimize(self, net, data):
        # the base loop doesn't expose x between steps; the (s, y) history
        # needs x deltas, so LBFGS runs its own copy of the loop
        from deeplearning4j_tpu.data.dataset import DataSet
        if isinstance(data, tuple):
            x, y = data
        elif isinstance(data, DataSet):
            x, y = data.features, data.labels
        else:
            raise ValueError("solver needs (features, labels) or a DataSet")
        x = jnp.asarray(np.asarray(x), net._compute_dtype)
        y = jnp.asarray(np.asarray(y), net._compute_dtype)
        vg = _flat_loss_fn(net, x, y)
        flat = param_util.params_to_flat(net.params)
        ls = BackTrackLineSearch(vg, self.max_line_search_iterations)
        state: dict = {}
        scores = []
        f0, g = vg(flat)
        scores.append(float(f0))
        it = 0
        for it in range(1, self.max_iterations + 1):
            direction = self._direction(g, state)
            step, new_flat, f_new = ls.optimize(flat, f0, g, direction)
            if step == 0.0:
                break
            state["x_delta"] = new_flat - flat
            flat = new_flat
            f_prev = float(f0)
            f0, g = vg(flat)
            scores.append(float(f0))
            if abs(f_prev - float(f0)) < self.tolerance * max(1.0, abs(f_prev)):
                break
        net.set_params_flat(flat)
        net._score = scores[-1]
        return _SolverResult(scores=scores, iterations=it)
