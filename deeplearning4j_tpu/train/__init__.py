from deeplearning4j_tpu.train.listeners import (
    TrainingListener, ScoreIterationListener, PerformanceListener,
    CollectScoresIterationListener, TimeIterationListener,
    EvaluativeListener, CheckpointListener, ProfilerListener,
    DivergenceListener, TrainingDivergedError,
)
from deeplearning4j_tpu.train.solvers import (
    BackTrackLineSearch, ConjugateGradient, LBFGS, LineGradientDescent,
)

__all__ = [
    "TrainingListener", "ScoreIterationListener", "PerformanceListener",
    "CollectScoresIterationListener", "TimeIterationListener",
    "EvaluativeListener", "CheckpointListener", "ProfilerListener",
    "DivergenceListener", "TrainingDivergedError",
    "BackTrackLineSearch", "LineGradientDescent", "ConjugateGradient",
    "LBFGS",
]
