from deeplearning4j_tpu.train.listeners import (
    TrainingListener, ScoreIterationListener, PerformanceListener,
    CollectScoresIterationListener, TimeIterationListener,
    EvaluativeListener, CheckpointListener, ProfilerListener,
    DivergenceListener, TrainingDivergedError,
)
from deeplearning4j_tpu.train.resilience import (
    CheckpointManager, FaultPolicy, FitReport, PreemptionGuard,
    ResilientTrainer,
)
from deeplearning4j_tpu.train.solvers import (
    BackTrackLineSearch, ConjugateGradient, LBFGS, LineGradientDescent,
)

__all__ = [
    "TrainingListener", "ScoreIterationListener", "PerformanceListener",
    "CollectScoresIterationListener", "TimeIterationListener",
    "EvaluativeListener", "CheckpointListener", "ProfilerListener",
    "DivergenceListener", "TrainingDivergedError",
    "CheckpointManager", "FaultPolicy", "FitReport", "PreemptionGuard",
    "ResilientTrainer",
    "BackTrackLineSearch", "LineGradientDescent", "ConjugateGradient",
    "LBFGS",
]
