"""`python -m deeplearning4j_tpu.train` — CLI training entry
(ParallelWrapperMain.java analog; see train/cli.py)."""
from deeplearning4j_tpu.train.cli import main

raise SystemExit(main())
