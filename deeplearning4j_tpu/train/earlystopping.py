"""Early stopping — config-driven train-until-criteria loops.

Parity target: DL4J `deeplearning4j-nn/.../earlystopping/`:
`EarlyStoppingConfiguration` (builder w/ termination conditions, score
calculator, model saver, evaluate-every-N-epochs),
`trainer/BaseEarlyStoppingTrainer.java:47,77` (the epoch loop),
termination conditions (`MaxEpochsTerminationCondition`,
`MaxTimeIterationTerminationCondition`, `MaxScoreIterationTerminationCondition`,
`ScoreImprovementEpochTerminationCondition`, `BestScoreEpochTerminationCondition`),
savers (`InMemoryModelSaver`, `LocalFileModelSaver`), and
`EarlyStoppingResult`.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, List, Optional

import jax
import numpy as np


# ----------------------------------------------------------- score calculators
class ScoreCalculator:
    """Computes the model-selection score after each epoch (lower is better
    unless minimize=False). DL4J: DataSetLossCalculator etc."""
    minimize = True

    def calculate(self, model) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over a held-out iterator (DL4J DataSetLossCalculator)."""

    def __init__(self, data, batch_size: int = 32):
        self.data = data
        self.batch_size = batch_size

    def calculate(self, model) -> float:
        iterator = model._as_iterator(self.data, self.batch_size) \
            if not hasattr(self.data, "reset") else self.data
        total, count = 0.0, 0
        for ds in iterator:
            n = int(np.shape(ds.features)[0])
            total += model.score(ds) * n
            count += n
        iterator.reset()
        return total / max(count, 1)


class ClassificationScoreCalculator(ScoreCalculator):
    """Maximize accuracy/f1 on held-out data (DL4J ClassificationScoreCalculator)."""
    minimize = False

    def __init__(self, data, metric: str = "accuracy", batch_size: int = 32):
        self.data = data
        self.metric = metric
        self.batch_size = batch_size

    def calculate(self, model) -> float:
        ev = model.evaluate(self.data, batch_size=self.batch_size)
        return float(getattr(ev, self.metric)())


# ------------------------------------------------------ termination conditions
class EpochTerminationCondition:
    # Conditions that compare the epoch SCORE set uses_score = True; they are
    # only consulted on epochs where a score was actually computed (eval
    # epochs when a score calculator is configured). Epoch/time-count
    # conditions leave it False and run every epoch.
    uses_score = False

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def terminate(self, iteration: int, score: float, elapsed_s: float) -> bool:
        raise NotImplementedError


@dataclasses.dataclass
class MaxEpochsTerminationCondition(EpochTerminationCondition):
    max_epochs: int

    def terminate(self, epoch, score):
        return epoch >= self.max_epochs - 1


@dataclasses.dataclass
class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without (min_improvement) improvement."""
    max_epochs_without_improvement: int
    min_improvement: float = 0.0
    uses_score = True

    def __post_init__(self):
        self._best: Optional[float] = None
        self._since = 0
        self.minimize = True

    def terminate(self, epoch, score):
        s = score if self.minimize else -score
        if self._best is None or s < self._best - self.min_improvement:
            self._best = s
            self._since = 0
            return False
        self._since += 1
        return self._since > self.max_epochs_without_improvement


@dataclasses.dataclass
class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop as soon as the score is at least as good as a target."""
    best_expected_score: float
    minimize: bool = True
    uses_score = True

    def terminate(self, epoch, score):
        return score <= self.best_expected_score if self.minimize \
            else score >= self.best_expected_score


@dataclasses.dataclass
class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    max_seconds: float

    def terminate(self, iteration, score, elapsed_s):
        return elapsed_s >= self.max_seconds


@dataclasses.dataclass
class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort on divergence: training loss exceeds a bound (or NaN)."""
    max_score: float

    def terminate(self, iteration, score, elapsed_s):
        return not np.isfinite(score) or score > self.max_score


# --------------------------------------------------------------------- savers
class InMemoryModelSaver:
    """DL4J InMemoryModelSaver: keep best/latest model copies in memory."""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best(self, model):
        self._best = (jax.tree_util.tree_map(lambda a: a, model.params),
                      jax.tree_util.tree_map(lambda a: a, model.state))

    def save_latest(self, model):
        self._latest = (jax.tree_util.tree_map(lambda a: a, model.params),
                        jax.tree_util.tree_map(lambda a: a, model.state))

    def restore_best(self, model):
        if self._best is None:
            return model
        model.params, model.state = self._best
        return model


class LocalFileModelSaver:
    """DL4J LocalFileModelSaver: bestModel.zip / latestModel.zip on disk."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def save_best(self, model):
        from deeplearning4j_tpu.util.serialization import save_model
        save_model(model, os.path.join(self.directory, "bestModel.zip"))

    def save_latest(self, model):
        from deeplearning4j_tpu.util.serialization import save_model
        save_model(model, os.path.join(self.directory, "latestModel.zip"))

    def restore_best(self, model):
        from deeplearning4j_tpu.util.serialization import load_model
        return load_model(os.path.join(self.directory, "bestModel.zip"))


# --------------------------------------------------------------------- config
@dataclasses.dataclass
class EarlyStoppingConfiguration:
    """DL4J EarlyStoppingConfiguration.Builder analog."""
    score_calculator: Optional[ScoreCalculator] = None
    epoch_termination_conditions: List[EpochTerminationCondition] = \
        dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = \
        dataclasses.field(default_factory=list)
    model_saver: Any = dataclasses.field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    """DL4J EarlyStoppingResult: why we stopped + best model info."""
    termination_reason: str          # "epoch" | "iteration" | "exhausted"
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: Any


class EarlyStoppingTrainer:
    """DL4J BaseEarlyStoppingTrainer: epoch loop + per-iteration divergence
    checks via a listener."""

    def __init__(self, config: EarlyStoppingConfiguration, model, data,
                 batch_size: int = 32):
        self.config = config
        self.model = model
        self.data = data
        self.batch_size = batch_size

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        model = self.model
        if model.params is None:
            model.init()
        calc = cfg.score_calculator
        best_score = None
        best_epoch = -1
        score_history = {}
        start = time.monotonic()
        epoch = 0
        reason, details = "exhausted", "no termination condition fired"

        # iteration-level divergence/time guard (DL4J checks inside the
        # iteration listener)
        class _Guard:
            stop = False
            why = ""

            def on_epoch_start(self, *a): pass
            def on_epoch_end(self, *a): pass

            def iteration_done(_self, m, it, ep, score, etl, bs):
                elapsed = time.monotonic() - start
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(it, score, elapsed):
                        _self.stop = True
                        _self.why = f"{type(c).__name__} at iteration {it}"

        guard = _Guard()
        saved_listeners = list(model.listeners)
        model.listeners = saved_listeners + [guard]
        try:
            while True:
                model.fit(self.data, epochs=1, batch_size=self.batch_size)
                if guard.stop:
                    reason, details = "iteration", guard.why
                    break
                do_eval = (epoch % cfg.evaluate_every_n_epochs == 0)
                if calc and not do_eval:
                    # With a score calculator configured, skipped-eval epochs
                    # do NOT substitute the training loss — it's on a
                    # different scale (and direction) than the validation
                    # score, so best-model selection and score-based
                    # termination only run on eval epochs (DL4J
                    # BaseEarlyStoppingTrainer behavior).
                    if cfg.save_last_model:
                        cfg.model_saver.save_latest(model)
                    fired = None
                    for c in cfg.epoch_termination_conditions:
                        if not c.uses_score:    # epoch/time-count conditions
                            if c.terminate(epoch, float("nan")):
                                fired = c
                                break
                    if fired is not None:
                        reason = "epoch"
                        details = f"{type(fired).__name__} at epoch {epoch}"
                        break
                    epoch += 1
                    continue
                # graftlint: disable=host-sync-in-hot-path -- ONE per-epoch score materialization (not per-step); everything below reuses the host float
                score = float(calc.calculate(model) if calc
                              else model.score())
                minimize = calc.minimize if calc else True
                score_history[epoch] = score
                better = (best_score is None or
                          (score < best_score if minimize else score > best_score))
                if better:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best(model)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest(model)
                fired = None
                for c in cfg.epoch_termination_conditions:
                    if c.uses_score and hasattr(c, "minimize"):
                        c.minimize = minimize
                    if c.terminate(epoch, score):
                        fired = c
                        break
                if fired is not None:
                    reason = "epoch"
                    details = f"{type(fired).__name__} at epoch {epoch}"
                    break
                epoch += 1
        finally:
            model.listeners = saved_listeners
        best_model = cfg.model_saver.restore_best(model)
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch + 1,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_score is not None else float("nan"),
            score_vs_epoch=score_history,
            best_model=best_model,
        )
