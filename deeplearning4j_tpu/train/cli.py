"""CLI training entry point.

Parity: DL4J `deeplearning4j-scaleout-parallelwrapper/.../main/
ParallelWrapperMain.java` (143 LoC): args-driven launcher — model zip in,
worker/averaging knobs, fit over a data source, save the trained model.

Usage:
    python -m deeplearning4j_tpu.train \
        --model model.zip --output trained.zip \
        --dataset mnist --epochs 2 --batch-size 64 \
        --mode sync --averaging-frequency 5 --ui-port 9001
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.train",
        description="Train a serialized model with the ParallelWrapper "
                    "data-parallel trainer (ParallelWrapperMain analog)")
    p.add_argument("--model", required=True,
                   help="input model zip (save_model format)")
    p.add_argument("--output", required=True,
                   help="where to write the trained model zip")
    p.add_argument("--dataset", required=True,
                   help="mnist | emnist | cifar10 | iris | path to .npz "
                        "with 'features' and 'labels' arrays")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--mode", choices=["sync", "averaging", "single"],
                   default="sync",
                   help="sync = compiled all-reduce DP; averaging = DL4J "
                        "AVERAGING semantics; single = plain net.fit")
    p.add_argument("--averaging-frequency", type=int, default=5)
    p.add_argument("--no-average-updaters", action="store_true",
                   help="skip averaging optimizer state (saveUpdater=false)")
    p.add_argument("--ui-port", type=int, default=None,
                   help="serve the training dashboard on this port")
    p.add_argument("--score-every", type=int, default=10,
                   help="ScoreIterationListener frequency")
    p.add_argument("--synthetic-data", action="store_true",
                   help="substitute deterministic synthetic data when the "
                        "dataset cache is missing (pipeline testing only); "
                        "without this flag a missing cache is an error")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable fault-tolerant training (ResilientTrainer): "
                        "atomic manifest-tracked checkpoints in this "
                        "directory, SIGTERM/SIGINT preemption handling, "
                        "per-step fault policy (docs/FAULT_TOLERANCE.md)")
    p.add_argument("--resume", action="store_true",
                   help="auto-resume from the newest valid checkpoint in "
                        "--checkpoint-dir (bitwise-identical continuation); "
                        "--epochs is then the TOTAL epoch target")
    p.add_argument("--save-every-iterations", type=int, default=50,
                   help="checkpoint cadence for --checkpoint-dir runs")
    p.add_argument("--keep-last", type=int, default=3,
                   help="checkpoints retained by manifest pruning")
    p.add_argument("--metrics", action="store_true",
                   help="print the final telemetry summary "
                        "(monitor.summary()) as JSON to stderr; with "
                        "--ui-port the live Prometheus exposition is "
                        "also served at /metrics (docs/OBSERVABILITY.md)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="record telemetry spans and write a Chrome "
                        "trace-event JSON to PATH on exit (load in "
                        "Perfetto / chrome://tracing)")
    p.add_argument("--perf-ledger", default=None, metavar="PATH",
                   help="enable the compiled-program ledger (monitor.xla: "
                        "per-program fingerprint, compile time, flops, "
                        "bytes accessed, HBM peak; live train_mfu_pct) and "
                        "write the ledger JSON to PATH on exit; defaults "
                        "to perf_ledger.json alongside --trace-out when "
                        "tracing is on (docs/OBSERVABILITY.md, gate it "
                        "with tools/perf_report.py)")
    p.add_argument("--serve-port", type=int, default=None,
                   help="after a successful fit, serve the trained model "
                        "over HTTP on this port (shape-bucketed batching, "
                        "warmed; docs/SERVING.md) until SIGTERM/SIGINT, "
                        "then drain gracefully")
    p.add_argument("--serve-buckets", default="1,8,32,128",
                   help="batch-size bucket ladder for --serve-port")
    p.add_argument("--mesh", default=None, metavar="SPEC",
                   help="GSPMD sharding plan for the whole run, e.g. "
                        "'data=8' or 'data=4,model=2,rules=megatron,"
                        "zero=1' — the plan compiles into the default "
                        "fit() (DP all-reduce, Megatron TP, ZeRO "
                        "reduce-scatter/all-gather as jit-inserted "
                        "collectives; docs/PARALLELISM.md). Applies to "
                        "--mode single|sync and the resilient path")
    return p


def _serve_trained(net, args) -> None:
    """train -> serve handoff: publish the just-trained model on
    --serve-port and block until a signal requests a graceful drain."""
    import signal
    import threading

    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
    registry = ModelRegistry()
    registry.deploy("model", net, buckets=args.serve_buckets)
    server = ModelServer(registry, port=args.serve_port)
    print(json.dumps({"serving": server.url,
                      "predict": "/v1/models/model/predict"}),
          file=sys.stderr)
    stop = threading.Event()
    for s in (signal.SIGTERM, signal.SIGINT):
        signal.signal(s, lambda *_: stop.set())
    stop.wait()
    server.drain()


def _load_data(name: str, batch_size: int, allow_synthetic: bool = False):
    from deeplearning4j_tpu.data.fetchers import (
        Cifar10DataSetIterator, EmnistDataSetIterator, IrisDataSetIterator,
        MnistDataSetIterator,
    )
    from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator
    # a real training CLI must not silently train on synthetic noise: the
    # fetchers' lenient default is overridden to fail loudly unless the
    # user opted in with --synthetic-data
    syn = None if allow_synthetic else False
    builtin = {
        "mnist": lambda: MnistDataSetIterator(batch_size=batch_size,
                                              synthetic=syn),
        "emnist": lambda: EmnistDataSetIterator(batch_size=batch_size,
                                                synthetic=syn),
        "cifar10": lambda: Cifar10DataSetIterator(batch_size=batch_size,
                                                  synthetic=syn),
        "iris": lambda: IrisDataSetIterator(batch_size=batch_size),
    }
    if name.lower() in builtin:
        return builtin[name.lower()]()
    data = np.load(name)
    if "features" not in data or "labels" not in data:
        raise SystemExit(f"{name}: npz must contain 'features' and 'labels'")
    return ArrayDataSetIterator(data["features"], data["labels"],
                                batch_size=batch_size)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # validate BEFORE the (possibly hours-long) fit: a typo'd ladder must
    # not surface only when the post-training serve handoff starts
    try:
        args.serve_buckets = tuple(
            int(b) for b in args.serve_buckets.split(",") if b)
    except ValueError:
        raise SystemExit(f"--serve-buckets must be comma-separated ints, "
                         f"got {args.serve_buckets!r}")
    import os

    import jax
    if os.environ.get("JAX_PLATFORMS"):
        # the axon TPU plugin force-appends itself to jax_platforms at
        # import, overriding the env var — pin the user's choice back
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from deeplearning4j_tpu import monitor
    from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode
    from deeplearning4j_tpu.train.listeners import (
        PerformanceListener, ScoreIterationListener,
    )
    from deeplearning4j_tpu.util.serialization import load_model, save_model

    if args.trace_out:
        monitor.enable_tracing()
    if args.perf_ledger is None and args.trace_out:
        # "alongside --trace-out": tracing runs double as perf-ledger runs
        # unless the user points the ledger elsewhere
        args.perf_ledger = os.path.join(
            os.path.dirname(os.path.abspath(args.trace_out)),
            "perf_ledger.json")
    if args.perf_ledger:
        monitor.xla.enable_ledger(args.perf_ledger)
        # the ledger's captures are AOT lower+compile calls that bypass
        # the jit __call__ cache — share bench's persistent XLA compile
        # cache so they are disk hits, not multi-minute TPU recompiles
        try:
            from bench import cache_dir
            jax.config.update(
                "jax_compilation_cache_dir",
                os.environ.get("JAX_COMPILATION_CACHE_DIR", cache_dir()))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 2.0)
        except Exception:
            pass    # bench.py not importable (installed package): skip

    def emit_telemetry():
        # runs in a finally: a bad --trace-out path (unwritable dir, full
        # disk) must not fail an otherwise-successful run or mask the
        # fit's real exception
        if args.trace_out:
            try:
                n = monitor.save_trace(args.trace_out)
                print(f"trace: {args.trace_out} ({n} events)",
                      file=sys.stderr)
            except OSError as e:
                print(f"trace not written to {args.trace_out}: {e}",
                      file=sys.stderr)
        if args.perf_ledger:
            try:
                n = monitor.xla.save_ledger(args.perf_ledger)
                print(f"perf ledger: {args.perf_ledger} ({n} programs)",
                      file=sys.stderr)
            except OSError as e:
                print(f"perf ledger not written to {args.perf_ledger}: {e}",
                      file=sys.stderr)
        if args.metrics:
            print(json.dumps({"metrics": monitor.summary()}),
                  file=sys.stderr)

    net = load_model(args.model)
    iterator = _load_data(args.dataset, args.batch_size,
                          allow_synthetic=args.synthetic_data)
    listeners = [ScoreIterationListener(args.score_every),
                 PerformanceListener(args.score_every)]
    ui_server = None
    if args.ui_port is not None:
        from deeplearning4j_tpu.ui import (
            InMemoryStatsStorage, StatsListener, UIServer,
        )
        storage = InMemoryStatsStorage()
        listeners.append(StatsListener(storage, frequency=args.score_every))
        ui_server = UIServer(port=args.ui_port)   # serves once constructed
        ui_server.attach(storage)
        print(f"dashboard: {ui_server.url}", file=sys.stderr)
    net.set_listeners(*listeners)

    if args.resume and not args.checkpoint_dir:
        raise SystemExit("--resume requires --checkpoint-dir")
    # --mesh: the whole training section runs under use_mesh so plain
    # fit(), ParallelWrapper and ResilientTrainer all resolve the plan
    # with zero further wiring (parallel/plan.active_plan)
    mesh_ctx = None
    if args.mesh:
        from deeplearning4j_tpu.parallel.plan import parse_plan, use_mesh
        try:
            mesh_plan = parse_plan(args.mesh)
            mesh_plan.mesh()    # validate extents against the REAL device
            # count now — "data=16 on an 8-chip host" must be a clean
            # SystemExit before the (possibly hours-long) fit, not a raw
            # traceback mid-run
        except ValueError as e:
            raise SystemExit(f"--mesh: {e}")
        if args.mode == "averaging":
            raise SystemExit("--mesh applies to --mode single|sync "
                             "(AVERAGING keeps per-worker replicas by "
                             "definition)")
        mesh_ctx = use_mesh(mesh_plan)
        mesh_ctx.__enter__()        # exited in the finally below
        print(f"mesh plan: {mesh_plan.describe()}", file=sys.stderr)
    # telemetry emits in a finally: a fit that dies mid-run (bad data,
    # retries exhausted, OOM) still leaves the trace/metrics record —
    # the crash case is exactly when it is most needed
    try:
        if args.checkpoint_dir:
            # resilient path: atomic checkpoint/auto-resume + fault policy;
            # wraps the plain net (single) or the sync-mode ParallelWrapper
            from deeplearning4j_tpu.train.resilience import ResilientTrainer
            target = net
            if args.mode == "sync":
                target = ParallelWrapper(net,
                                         mode=TrainingMode.SYNC_GRADIENTS)
            elif args.mode == "averaging":
                raise SystemExit("--checkpoint-dir supports --mode "
                                 "single|sync (AVERAGING replica state is "
                                 "not resumable)")
            trainer = ResilientTrainer(
                target, args.checkpoint_dir,
                save_every_n_iterations=args.save_every_iterations,
                keep_last=args.keep_last, resume=args.resume)
            report = trainer.fit(iterator, epochs=args.epochs,
                                 batch_size=args.batch_size)
            if report.preempted or report.diverged:
                # incomplete run (preempted, or diverged and rolled back
                # to an older checkpoint): no output model, no success
                # JSON, distinct exit code so callers can't mistake it
                # for a finished job
                print(json.dumps({"preempted": report.preempted,
                                  "diverged": report.diverged,
                                  "iterations": net.iteration_count,
                                  "resume_with": "--resume"}),
                      file=sys.stderr)
                if ui_server is not None:
                    ui_server.stop()
                return 3 if report.preempted else 4
        elif args.mode == "single":
            net.fit(iterator, epochs=args.epochs)
        else:
            wrapper = ParallelWrapper(
                net,
                mode=(TrainingMode.SYNC_GRADIENTS if args.mode == "sync"
                      else TrainingMode.AVERAGING),
                averaging_frequency=args.averaging_frequency,
                average_updaters=not args.no_average_updaters)
            wrapper.fit(iterator, epochs=args.epochs)

        save_model(net, args.output)
        print(json.dumps({"output": args.output,
                          "final_score": net.score(),
                          "iterations": net.iteration_count,
                          "epochs": net.epoch_count}))
        if args.serve_port is not None:
            _serve_trained(net, args)
        if ui_server is not None:
            ui_server.stop()
        return 0
    finally:
        if mesh_ctx is not None:
            mesh_ctx.__exit__(None, None, None)
        emit_telemetry()


if __name__ == "__main__":
    raise SystemExit(main())
