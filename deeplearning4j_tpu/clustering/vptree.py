"""Vantage-point tree (DL4J `clustering/vptree/VPTree.java`).

Exact metric-space nearest neighbors: build picks a vantage point and
splits at the median distance; search prunes with the triangle inequality.
Host-side recursive structure (SURVEY.md §7: tree algorithms stay host-
native); numpy vectorizes the distance evaluations.
"""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index, threshold, inside, outside):
        self.index = index
        self.threshold = threshold
        self.inside = inside
        self.outside = outside


def _dist(a, b, metric):
    if metric == "euclidean":
        return float(np.linalg.norm(a - b))
    if metric == "cosine":
        na, nb = np.linalg.norm(a), np.linalg.norm(b)
        if na == 0 or nb == 0:
            return 1.0
        return float(1.0 - a @ b / (na * nb))
    raise ValueError(metric)


class VPTree:
    def __init__(self, points, metric: str = "euclidean", seed: int = 0):
        self.points = np.asarray(points, np.float32)
        self.metric = metric
        rs = np.random.RandomState(seed)
        self.root = self._build(list(range(len(self.points))), rs)

    def _build(self, idxs: List[int], rs) -> Optional[_Node]:
        if not idxs:
            return None
        vp = idxs[rs.randint(len(idxs))]
        rest = [i for i in idxs if i != vp]
        if not rest:
            return _Node(vp, 0.0, None, None)
        dists = np.linalg.norm(self.points[rest] - self.points[vp], axis=1) \
            if self.metric == "euclidean" else np.asarray(
                [_dist(self.points[i], self.points[vp], self.metric)
                 for i in rest])
        thr = float(np.median(dists))
        inside = [rest[i] for i in range(len(rest)) if dists[i] <= thr]
        outside = [rest[i] for i in range(len(rest)) if dists[i] > thr]
        return _Node(vp, thr, self._build(inside, rs),
                     self._build(outside, rs))

    def knn(self, query, k: int = 1) -> Tuple[List[int], List[float]]:
        """k nearest neighbors (DL4J VPTree.search)."""
        query = np.asarray(query, np.float32)
        heap: List[Tuple[float, int]] = []    # max-heap via negated dist
        tau = [np.inf]

        def visit(node):
            if node is None:
                return
            d = _dist(query, self.points[node.index], self.metric)
            if d < tau[0] or len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) > k:
                    heapq.heappop(heap)
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d <= node.threshold:
                visit(node.inside)
                if d + tau[0] > node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in out], [d for d, _ in out]
