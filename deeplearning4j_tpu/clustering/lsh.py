"""Locality-sensitive hashing (DL4J `clustering/lsh/RandomProjectionLSH.java`).

Sign-of-random-projection signatures with multi-table lookup; candidate
re-ranking uses exact distances (vectorized numpy).
"""
from __future__ import annotations

from collections import defaultdict
from typing import List, Tuple

import numpy as np


class RandomProjectionLSH:
    def __init__(self, hash_length: int = 16, num_tables: int = 4,
                 seed: int = 0):
        self.hash_length = hash_length
        self.num_tables = num_tables
        self.seed = seed
        self._planes = None
        self._tables = None
        self.points = None

    def _signatures(self, X) -> np.ndarray:
        """(T, N) int signatures from sign patterns."""
        bits = (np.einsum("tfd,nd->tnf", self._planes, X) > 0)
        weights = 1 << np.arange(self.hash_length)
        return (bits * weights).sum(-1)

    def fit(self, points) -> "RandomProjectionLSH":
        self.points = np.asarray(points, np.float32)
        d = self.points.shape[1]
        rs = np.random.RandomState(self.seed)
        self._planes = rs.randn(self.num_tables, self.hash_length,
                                d).astype(np.float32)
        sigs = self._signatures(self.points)
        self._tables = [defaultdict(list) for _ in range(self.num_tables)]
        for t in range(self.num_tables):
            for i, s in enumerate(sigs[t]):
                self._tables[t][int(s)].append(i)
        return self

    def query(self, x, k: int = 5) -> Tuple[List[int], List[float]]:
        x = np.asarray(x, np.float32)
        sigs = self._signatures(x[None])[:, 0]
        cands = set()
        for t in range(self.num_tables):
            cands.update(self._tables[t].get(int(sigs[t]), ()))
        if not cands:
            cands = set(range(len(self.points)))   # degenerate fallback
        cand = np.asarray(sorted(cands))
        dists = np.linalg.norm(self.points[cand] - x, axis=1)
        order = np.argsort(dists)[:k]
        return [int(cand[i]) for i in order], [float(dists[i]) for i in order]
