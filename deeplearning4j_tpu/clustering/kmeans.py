"""K-means (DL4J `clustering/kmeans/KMeansClustering.java` + the
clustering/algorithm framework it instantiates).

Lloyd's algorithm with k-means++ seeding; the assignment step (pairwise
distances + argmin) is one jit-compiled device program per iteration.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _assign(points, centers):
    """(N, D) x (K, D) -> (N,) nearest-center ids + distances (device)."""
    d2 = (jnp.sum(points ** 2, 1)[:, None]
          - 2.0 * points @ centers.T
          + jnp.sum(centers ** 2, 1)[None, :])
    idx = jnp.argmin(d2, axis=1)
    return idx, jnp.sqrt(jnp.maximum(jnp.take_along_axis(
        d2, idx[:, None], 1)[:, 0], 0.0))


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100,
                 tolerance: float = 1e-4, seed: int = 0,
                 distance: str = "euclidean"):
        if distance not in ("euclidean",):
            raise ValueError("only euclidean distance is supported")
        self.k = k
        self.max_iterations = max_iterations
        self.tolerance = tolerance
        self.seed = seed
        self.centers: Optional[np.ndarray] = None
        self.iterations_done = 0

    def _init_centers(self, X, rs):
        """k-means++ seeding (pairwise distances via the native host
        kernel when built — O(N·K) memory instead of numpy's N×K×D
        broadcast temporary)."""
        from ..native.ndarray import pairwise_sqdist
        n = len(X)
        centers = [X[rs.randint(n)]]
        for _ in range(1, self.k):
            d2 = pairwise_sqdist(X, np.asarray(centers)).min(axis=1)
            d2 = d2.astype(np.float64)   # rs.choice needs probs Σ=1 to 1e-8
            probs = d2 / max(d2.sum(), 1e-12)
            centers.append(X[rs.choice(n, p=probs)])
        return np.asarray(centers, np.float32)

    def fit(self, X) -> "KMeansClustering":
        X = np.asarray(X, np.float32)
        rs = np.random.RandomState(self.seed)
        centers = self._init_centers(X, rs)
        Xd = jnp.asarray(X)
        for it in range(self.max_iterations):
            idx, _ = _assign(Xd, jnp.asarray(centers))
            idx = np.asarray(idx)
            new_centers = centers.copy()
            for c in range(self.k):
                members = X[idx == c]
                if len(members):
                    new_centers[c] = members.mean(0)
            # graftlint: disable=host-sync-in-hot-path -- host numpy math on host-resident centers (the device assignment was materialized by np.asarray(idx) above), not a device fetch
            shift = float(np.linalg.norm(new_centers - centers))
            centers = new_centers
            self.iterations_done = it + 1
            if shift < self.tolerance:
                break
        self.centers = centers
        return self

    def predict(self, X) -> np.ndarray:
        idx, _ = _assign(jnp.asarray(np.asarray(X, np.float32)),
                         jnp.asarray(self.centers))
        return np.asarray(idx)

    def inertia(self, X) -> float:
        _, d = _assign(jnp.asarray(np.asarray(X, np.float32)),
                       jnp.asarray(self.centers))
        return float(jnp.sum(d ** 2))
