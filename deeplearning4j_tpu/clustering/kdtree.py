"""KD-tree (DL4J `clustering/kdtree/KDTree.java`)."""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _KDNode:
    __slots__ = ("index", "axis", "left", "right")

    def __init__(self, index, axis, left, right):
        self.index = index
        self.axis = axis
        self.left = left
        self.right = right


class KDTree:
    def __init__(self, points):
        self.points = np.asarray(points, np.float32)
        self.root = self._build(list(range(len(self.points))), 0)

    def _build(self, idxs: List[int], depth: int) -> Optional[_KDNode]:
        if not idxs:
            return None
        axis = depth % self.points.shape[1]
        idxs.sort(key=lambda i: self.points[i, axis])
        mid = len(idxs) // 2
        return _KDNode(idxs[mid], axis,
                       self._build(idxs[:mid], depth + 1),
                       self._build(idxs[mid + 1:], depth + 1))

    def nn(self, query) -> Tuple[int, float]:
        idxs, dists = self.knn(query, 1)
        return idxs[0], dists[0]

    def knn(self, query, k: int = 1) -> Tuple[List[int], List[float]]:
        query = np.asarray(query, np.float32)
        heap: List[Tuple[float, int]] = []

        def visit(node):
            if node is None:
                return
            d = float(np.linalg.norm(query - self.points[node.index]))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = query[node.axis] - self.points[node.index, node.axis]
            near, far = (node.left, node.right) if diff <= 0 \
                else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        return [i for _, i in out], [d for d, _ in out]
