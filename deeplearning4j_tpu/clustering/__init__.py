"""Nearest neighbors + clustering (DL4J deeplearning4j-nearestneighbors parity).

Reference: `deeplearning4j-nearestneighbors-parent/nearestneighbor-core/
.../clustering/{vptree,kdtree,kmeans,lsh,randomprojection,sptree}`.

Placement policy (SURVEY.md §7 hard parts): tree construction and traversal
are host algorithms and stay host-side (numpy); the distance kernels that
dominate k-means and brute-force search run on device (one jit-compiled
pairwise-distance matmul per iteration — the MXU eats these).
"""
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.lsh import RandomProjectionLSH
from deeplearning4j_tpu.clustering.randomprojection import RandomProjection
from deeplearning4j_tpu.clustering.server import (
    NearestNeighborsClient, NearestNeighborsServer,
)

__all__ = ["KMeansClustering", "VPTree", "KDTree", "RandomProjectionLSH",
           "RandomProjection", "NearestNeighborsServer",
           "NearestNeighborsClient"]
