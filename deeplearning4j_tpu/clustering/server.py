"""Nearest-neighbors REST server + client.

Parity: DL4J `deeplearning4j-nearestneighbor-server/.../NearestNeighborsServer.java:42`
(Play routes `POST /knn` — k neighbors of an already-indexed point — and
`POST /knnnew` — k neighbors of a new vector) and the matching
`deeplearning4j-nearestneighbor-client`. TPU-native redesign: stdlib
ThreadingHTTPServer over the in-tree VPTree/KDTree (ui/server.py pattern —
zero external deps), JSON instead of the reference's binary ndarray wire
format.

Routes:
    GET  /health          -> {"status": "ok", "points": N, "dim": D}
    POST /knn             {"index": i, "k": k}   -> {"results": [...]}
    POST /knnnew          {"arr": [...], "k": k} -> {"results": [...]}
    POST /insert          {"arr": [...]}          -> {"index": new_index}
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib import request as urlrequest

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


class NearestNeighborsServer:
    """Serve k-NN queries over a point set (NearestNeighborsServer.java:42).

    Inserts are accepted into a side buffer that is linearly scanned and
    merged with the VP-tree results, so /insert is O(1) and the tree is
    rebuilt lazily only when the buffer outgrows `rebuild_threshold`.
    """

    def __init__(self, points, port: int = 0, metric: str = "euclidean",
                 rebuild_threshold: int = 256):
        self.points = np.asarray(points, np.float32)
        self.metric = metric
        self.rebuild_threshold = rebuild_threshold
        self._tree = VPTree(self.points, metric=metric)
        self._extra: List[np.ndarray] = []
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.port = port

    # --------------------------------------------------------------- knn
    def _all_points_locked(self) -> np.ndarray:
        """Caller must hold self._lock (insert() rebuilds in two steps)."""
        if not self._extra:
            return self.points
        return np.concatenate([self.points, np.stack(self._extra)])

    def _all_points(self) -> np.ndarray:
        with self._lock:
            return self._all_points_locked()

    def knn_index(self, index: int, k: int):
        with self._lock:
            pts = self._all_points_locked()
            if not 0 <= index < len(pts):
                raise IndexError(f"index {index} out of range ({len(pts)})")
            vec = pts[index]
        return self.knn_vector(vec, k)

    def knn_vector(self, vec, k: int):
        from deeplearning4j_tpu.clustering.vptree import _dist
        vec = np.asarray(vec, np.float32)
        with self._lock:
            idxs, dists = self._tree.knn(vec, k)
            results = list(zip(idxs, dists))
            base = len(self.points)
            # side buffer scanned with the SAME metric as the tree
            for j, p in enumerate(self._extra):
                results.append((base + j, float(_dist(vec, p, self.metric))))
        results.sort(key=lambda r: r[1])
        return results[:k]

    def insert(self, vec) -> int:
        vec = np.asarray(vec, np.float32)
        if vec.shape != (self.points.shape[1],):
            raise ValueError(f"expected dim {self.points.shape[1]}, "
                             f"got {vec.shape}")
        with self._lock:
            self._extra.append(vec)
            idx = len(self.points) + len(self._extra) - 1
            if len(self._extra) >= self.rebuild_threshold:
                self.points = self._all_points_locked()
                self._tree = VPTree(self.points, metric=self.metric)
                self._extra = []
        return idx

    # ------------------------------------------------------------- serve
    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # silence request logging
                pass

            def _json(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    pts = server._all_points()
                    self._json(200, {"status": "ok", "points": len(pts),
                                     "dim": int(pts.shape[1])})
                else:
                    self._json(404, {"error": "unknown route"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if self.path == "/knn":
                        res = server.knn_index(int(payload["index"]),
                                               int(payload.get("k", 1)))
                    elif self.path == "/knnnew":
                        res = server.knn_vector(payload["arr"],
                                                int(payload.get("k", 1)))
                    elif self.path == "/insert":
                        self._json(200,
                                   {"index": server.insert(payload["arr"])})
                        return
                    else:
                        self._json(404, {"error": "unknown route"})
                        return
                    self._json(200, {"results": [
                        {"index": int(i), "distance": float(d)}
                        for i, d in res]})
                except (KeyError, ValueError, IndexError) as e:
                    self._json(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="nn-server")
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class NearestNeighborsClient:
    """HTTP client for NearestNeighborsServer (the reference's
    nearestneighbor-client analog)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9000):
        self.base = f"http://{host}:{port}"

    def _post(self, route: str, payload: dict) -> dict:
        req = urlrequest.Request(
            self.base + route, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urlrequest.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def health(self) -> dict:
        with urlrequest.urlopen(self.base + "/health", timeout=30) as resp:
            return json.loads(resp.read())

    def knn(self, index: int, k: int = 1) -> List[dict]:
        return self._post("/knn", {"index": index, "k": k})["results"]

    def knn_new(self, vector, k: int = 1) -> List[dict]:
        return self._post("/knnnew",
                          {"arr": np.asarray(vector).tolist(),
                           "k": k})["results"]

    def insert(self, vector) -> int:
        return self._post("/insert",
                          {"arr": np.asarray(vector).tolist()})["index"]
