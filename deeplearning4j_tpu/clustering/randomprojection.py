"""Gaussian random projection (DL4J `clustering/randomprojection/RandomProjection.java`):
Johnson-Lindenstrauss dimensionality reduction."""
from __future__ import annotations

import numpy as np


def jl_target_dim(n_samples: int, eps: float = 0.1) -> int:
    """Johnson-Lindenstrauss minimum dimension (DL4J johnsonLindenstraussMinDim)."""
    return int(4 * np.log(n_samples) / (eps ** 2 / 2 - eps ** 3 / 3))


class RandomProjection:
    def __init__(self, target_dim: int, seed: int = 0):
        self.target_dim = target_dim
        self.seed = seed
        self._proj = None

    def fit(self, X) -> "RandomProjection":
        d = np.asarray(X).shape[1]
        rs = np.random.RandomState(self.seed)
        self._proj = (rs.randn(d, self.target_dim) /
                      np.sqrt(self.target_dim)).astype(np.float32)
        return self

    def transform(self, X) -> np.ndarray:
        if self._proj is None:
            self.fit(X)
        return np.asarray(X, np.float32) @ self._proj
