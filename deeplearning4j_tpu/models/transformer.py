"""TransformerLM — the long-context flagship model family.

No architecture analog in the DL4J zoo (its sequence model is
TextGenerationLSTM, `zoo/model/TextGenerationLSTM.java`); this is the
TPU-native successor: a decoder-only transformer LM designed around the
mesh —

- dp  : batch over "data" (ParallelWrapper),
- tp  : Megatron-style tensor parallelism over "model" via sharding rules
        (column-parallel Wq/Wk/Wv/W1, row-parallel Wo/W2 — XLA inserts the
        matched all-reduce pair),
- sp  : ring attention over "seq" (ContextParallelTrainer),
- ep  : MoE expert dim over "model" (MoEFeedForward stacks experts on a
        leading axis).
"""
from __future__ import annotations

import dataclasses

from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.models.zoo import ZooModel
from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import (
    EmbeddingSequenceLayer, LayerNormLayer, MoEFeedForward, RnnOutputLayer,
    TransformerBlock,
)
from deeplearning4j_tpu.nn.updaters import AdamW
from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS
from deeplearning4j_tpu.parallel.sharding import ShardingRules


@dataclasses.dataclass
class TransformerLM(ZooModel):
    """Decoder-only LM: token embedding -> n_layers TransformerBlocks
    (optionally interleaved MoE FFN blocks) -> LN -> tied-untied softmax head.

    Defaults sized for quick experiments; scale n_embd/n_layers/seq_length
    for real runs (keep n_embd a multiple of 128 for MXU tiling)."""
    vocab_size: int = 1024
    seq_length: int = 256
    n_layers: int = 4
    n_embd: int = 256
    n_heads: int = 8
    mlp_ratio: int = 4
    causal: bool = True
    use_rope: bool = True
    moe_every: int = 0          # 0 = dense; k>0 = every k-th block is MoE
    n_experts: int = 8
    dropout: float = 0.0
    learning_rate: float = 3e-4
    seed: int = 123
    attention_impl: str = "dense"
    block_size: int = 512

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(AdamW(self.learning_rate))
             .grad_clip_norm(1.0)
             .list())
        b.layer(EmbeddingSequenceLayer(n_out=self.n_embd,
                                       n_in=self.vocab_size))
        for i in range(self.n_layers):
            b.layer(TransformerBlock(
                n_out=self.n_embd, n_heads=self.n_heads,
                mlp_ratio=self.mlp_ratio, causal=self.causal,
                use_rope=self.use_rope,
                attention_dropout=self.dropout,
                residual_dropout=self.dropout,
                attention_impl=self.attention_impl,
                block_size=self.block_size))
            if self.moe_every and (i + 1) % self.moe_every == 0:
                b.layer(MoEFeedForward(n_out=self.n_embd,
                                       n_experts=self.n_experts,
                                       mlp_ratio=self.mlp_ratio))
        b.layer(LayerNormLayer())
        b.layer(RnnOutputLayer(n_out=self.vocab_size, activation="softmax",
                               loss="mcxent"))
        b.set_input_type(InputType.recurrent(1, self.seq_length))
        return b.build()

    @staticmethod
    def sharding_rules() -> ShardingRules:
        """Megatron tp + ep rules for the stack above. Paths look like
        "1/attn/Wq" (block params are nested dicts)."""
        return ShardingRules((
            # attention: column-parallel QKV, row-parallel output
            (r".*/attn/W[qkv]$", P(None, MODEL_AXIS)),
            (r".*/attn/Wo$", P(MODEL_AXIS, None)),
            # MoE (3D, leading expert dim): expert parallelism over "model".
            # Listed before the dense rules — spec_for skips a rule whose
            # spec is longer than the leaf's ndim, so 2D kernels fall through.
            (r".*/W1$", P(MODEL_AXIS, None, None)),
            (r".*/W2$", P(MODEL_AXIS, None, None)),
            # dense MLP: column-parallel up, row-parallel down
            (r".*/W1$", P(None, MODEL_AXIS)),
            (r".*/W2$", P(MODEL_AXIS, None)),
            # embedding: vocab-sharded
            (r"^0/W$", P(MODEL_AXIS, None)),
        ))


@dataclasses.dataclass
class TransformerLMMoE(TransformerLM):
    """Expert-parallel variant: every 2nd block followed by a top-2 MoE FFN."""
    moe_every: int = 2
    n_experts: int = 8
