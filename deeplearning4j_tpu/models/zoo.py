"""Canonical zoo architectures.

Parity target: DL4J `deeplearning4j-zoo/.../zoo/model/*.java` — LeNet
(`LeNet.java:83-95`), AlexNet, VGG16/19, GoogLeNet, ResNet50
(`ResNet50.java:33-76`), InceptionResNetV1/FaceNet, Darknet19, TinyYOLO,
YOLO2, SimpleCNN, TextGenerationLSTM, UNet.

Differences by design (TPU-first):
- NHWC activations everywhere (DL4J zoo is NCHW); weight layouts are HWIO.
- Batch norm / ReLU fusion is left to XLA; architectures are expressed as
  declarative configs, compiled as one XLA program per step.
- Pretrained-weight download URLs from the reference require network egress;
  `init_pretrained()` raises with a clear message when the cache is absent
  (DL4J ZooModel.initPretrained downloads from dl4jdata blob storage).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from deeplearning4j_tpu.nn.conf.base import InputType
from deeplearning4j_tpu.nn.conf.network import (
    ComputationGraphConfiguration, GraphBuilder, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf.graph_vertices import (
    ElementWiseVertex, MergeVertex, ScaleVertex,
)
from deeplearning4j_tpu.nn.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    DropoutLayer, GlobalPoolingLayer, LocalResponseNormalization, LSTM,
    OutputLayer, RnnOutputLayer, SpaceToDepthLayer, SubsamplingLayer,
    Upsampling2D, ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs


class ZooModel:
    """Base zoo model (DL4J `zoo/ZooModel.java`): `init()` builds an
    untrained network; `init_pretrained()` would load published weights."""

    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (224, 224, 3)
    seed: int = 123

    def conf(self):
        raise NotImplementedError

    def init(self):
        c = self.conf()
        if isinstance(c, ComputationGraphConfiguration):
            net = ComputationGraph(c)
        else:
            net = MultiLayerNetwork(c)
        return net.init()

    def init_pretrained(self, cache_dir: Optional[str] = None):
        """DL4J ZooModel.initPretrained downloads weight archives; this
        environment has no egress, so only a local cache can be used."""
        import os
        from deeplearning4j_tpu.util.serialization import load_model
        name = type(self).__name__.lower()
        cache_dir = cache_dir or os.path.expanduser("~/.deeplearning4j_tpu/models")
        path = os.path.join(cache_dir, f"{name}.zip")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"No pretrained weights cached at {path}; pretrained "
                "downloads require network access (DL4J ZooModel.initPretrained)")
        return load_model(path)


# --------------------------------------------------------------------- LeNet
@dataclasses.dataclass
class LeNet(ZooModel):
    """LeNet-5 on MNIST-sized input (DL4J `zoo/model/LeNet.java:83-95`)."""
    num_classes: int = 10
    input_shape: Tuple[int, int, int] = (28, 28, 1)
    seed: int = 123

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Adam(1e-3))
                .weight_init("xavier")
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel=(5, 5), stride=(1, 1),
                                        activation="identity"))
                .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2),
                                        pooling_type="max"))
                .layer(ConvolutionLayer(n_out=50, kernel=(5, 5), stride=(1, 1),
                                        activation="identity"))
                .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2),
                                        pooling_type="max"))
                .layer(DenseLayer(n_out=500, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes,
                                   activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(*self.input_shape))
                .build())


# ----------------------------------------------------------------- SimpleCNN
@dataclasses.dataclass
class SimpleCNN(ZooModel):
    """DL4J `zoo/model/SimpleCNN.java` — small VGG-ish CNN."""
    num_classes: int = 10
    input_shape: Tuple[int, int, int] = (48, 48, 3)
    seed: int = 123

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Adam(1e-3))
             .weight_init("relu")
             .list())
        for n_out, pool in ((16, False), (16, True), (32, False), (32, True),
                            (64, False), (64, True)):
            b.layer(ConvolutionLayer(n_out=n_out, kernel=(3, 3),
                                     convolution_mode="same",
                                     activation="identity"))
            b.layer(BatchNormalization())
            b.layer(ActivationLayer(activation="relu"))
            if pool:
                b.layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
        b.layer(DropoutLayer(dropout=0.5))
        b.layer(DenseLayer(n_out=256, activation="relu"))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent"))
        b.set_input_type(InputType.convolutional(*self.input_shape))
        return b.build()


# ------------------------------------------------------------------- AlexNet
@dataclasses.dataclass
class AlexNet(ZooModel):
    """AlexNet (DL4J `zoo/model/AlexNet.java`, one-tower variant with LRN)."""
    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (224, 224, 3)
    seed: int = 123

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Nesterovs(1e-2, momentum=0.9))
                .weight_init("relu")
                .l2(5e-4)
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel=(11, 11), stride=(4, 4),
                                        activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(kernel=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel=(5, 5),
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(kernel=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel=(3, 3),
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(ConvolutionLayer(n_out=384, kernel=(3, 3),
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(ConvolutionLayer(n_out=256, kernel=(3, 3),
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes,
                                   activation="softmax", loss="mcxent"))
                .set_input_type(InputType.convolutional(*self.input_shape))
                .build())


# ----------------------------------------------------------------- VGG 16/19
def _vgg_conf(blocks, num_classes, input_shape, seed):
    b = (NeuralNetConfiguration.Builder()
         .seed(seed)
         .updater(Nesterovs(1e-2, momentum=0.9))
         .weight_init("relu")
         .list())
    for n_convs, n_out in blocks:
        for _ in range(n_convs):
            b.layer(ConvolutionLayer(n_out=n_out, kernel=(3, 3),
                                     convolution_mode="same",
                                     activation="relu"))
        b.layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
    b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
    b.layer(DenseLayer(n_out=4096, activation="relu", dropout=0.5))
    b.layer(OutputLayer(n_out=num_classes, activation="softmax", loss="mcxent"))
    b.set_input_type(InputType.convolutional(*input_shape))
    return b.build()


@dataclasses.dataclass
class VGG16(ZooModel):
    """VGG-16 (DL4J `zoo/model/VGG16.java`)."""
    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (224, 224, 3)
    seed: int = 123

    def conf(self):
        return _vgg_conf([(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)],
                         self.num_classes, self.input_shape, self.seed)


@dataclasses.dataclass
class VGG19(ZooModel):
    """VGG-19 (DL4J `zoo/model/VGG19.java`)."""
    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (224, 224, 3)
    seed: int = 123

    def conf(self):
        return _vgg_conf([(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)],
                         self.num_classes, self.input_shape, self.seed)


# ------------------------------------------------------------------ ResNet50
@dataclasses.dataclass
class ResNet50(ZooModel):
    """ResNet-50 (DL4J `zoo/model/ResNet50.java:33-76`).

    Bottleneck residual graph expressed as a ComputationGraph: conv blocks
    (projection shortcut) + identity blocks, batch norm after every conv.
    The whole forward/backward step compiles to a single XLA program; the
    residual adds are ElementWiseVertex(add) like DL4J's shortcut vertices.
    """
    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (224, 224, 3)
    seed: int = 123
    # MLPerf-style TPU stem: rearrange the input 2x2 space-to-depth and
    # replace the 7x7/s2 head conv (stride-2 convs underfill the MXU,
    # and C=3 wastes 125 of 128 input lanes) with a dense 4x4/s1 conv on
    # (112, 112, 12). EXACTLY equivalent to the standard stem under the
    # s2d_stem_weights() mapping (tested); opt-in because checkpoints
    # trained with one stem need that mapping to move to the other.
    space_to_depth_stem: bool = False

    def _conv_bn(self, g, name, n_out, kernel, stride, inp, pad="same",
                 relu=True):
        g.add_layer(f"{name}_conv",
                    ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                                     convolution_mode=pad,
                                     activation="identity", has_bias=False),
                    inp)
        g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
        if relu:
            g.add_layer(f"{name}_relu", ActivationLayer(activation="relu"),
                        f"{name}_bn")
            return f"{name}_relu"
        return f"{name}_bn"

    def _bottleneck(self, g, name, inp, filters, stride, project):
        f1, f2, f3 = filters
        x = self._conv_bn(g, f"{name}_a", f1, (1, 1), stride, inp)
        x = self._conv_bn(g, f"{name}_b", f2, (3, 3), (1, 1), x)
        x = self._conv_bn(g, f"{name}_c", f3, (1, 1), (1, 1), x, relu=False)
        if project:
            sc = self._conv_bn(g, f"{name}_sc", f3, (1, 1), stride, inp,
                               relu=False)
        else:
            sc = inp
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, sc)
        g.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                    f"{name}_add")
        return f"{name}_out"

    def conf(self):
        g = (GraphBuilder(NeuralNetConfiguration.Builder()
                          .seed(self.seed)
                          .updater(Nesterovs(1e-1, momentum=0.9))
                          .weight_init("relu")
                          .l2(1e-4))
             .add_inputs("input")
             .set_input_types(InputType.convolutional(*self.input_shape)))
        if self.space_to_depth_stem:
            h, w = self.input_shape[:2]
            if h % 2 or w % 2:
                raise ValueError(
                    f"space_to_depth_stem needs even input H/W (the 2x2 "
                    f"rearrange + exact 7x7-stem equivalence both require "
                    f"it); got {self.input_shape} — use the standard stem")
            g.add_layer("stem_s2d", SpaceToDepthLayer(block_size=2),
                        "input")
            # pad (2 left, 1 right): the 7x7+pad-3 receptive field spans
            # s2d cells i-2..i+1 (see s2d_stem_weights)
            g.add_layer("stem_pad", ZeroPaddingLayer(padding=(2, 1, 2, 1)),
                        "stem_s2d")
            x = self._conv_bn(g, "stem", 64, (4, 4), (1, 1), "stem_pad",
                              pad="truncate")
        else:
            g.add_layer("stem_pad", ZeroPaddingLayer(padding=(3, 3, 3, 3)),
                        "input")
            x = self._conv_bn(g, "stem", 64, (7, 7), (2, 2), "stem_pad",
                              pad="truncate")
        g.add_layer("stem_pool",
                    SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), x)
        x = "stem_pool"
        stages = [
            ("res2", (64, 64, 256), 3, (1, 1)),
            ("res3", (128, 128, 512), 4, (2, 2)),
            ("res4", (256, 256, 1024), 6, (2, 2)),
            ("res5", (512, 512, 2048), 3, (2, 2)),
        ]
        for sname, filters, blocks, stride in stages:
            x = self._bottleneck(g, f"{sname}a", x, filters, stride, True)
            for i in range(1, blocks):
                x = self._bottleneck(g, f"{sname}{chr(97 + i)}", x, filters,
                                     (1, 1), False)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes, activation="softmax",
                                loss="mcxent"), "avgpool")
        g.set_outputs("output")
        return g.build()


def s2d_stem_weights(w7):
    """Map the standard ResNet stem's (7, 7, C, F) HWIO conv weights onto
    the space-to-depth stem's (4, 4, 4*C, F) weights, EXACTLY:

    standard: out(i,j) = sum_{p,q<7} x_pad3[2i+p, 2j+q] . w7[p, q]
    s2d stem: the 4x4/s1 conv over pad-(2,1) s2d cells reads rows
    2i-4..2i+3; pad w7 to 8x8 with a zero leading row/col (k8[1:,1:] =
    w7) so that span contributes identically, then regroup
    w4[a, b, (dr*2+dc)*C + ch, f] = k8[2a+dr, 2b+dc, ch, f]
    matching SpaceToDepthLayer's (dr, dc, ch) depth order."""
    import numpy as np
    w7 = np.asarray(w7)
    kh, kw, c, f = w7.shape
    assert (kh, kw) == (7, 7), "stem mapping is for the 7x7 head conv"
    k8 = np.zeros((8, 8, c, f), w7.dtype)
    k8[1:, 1:] = w7
    # (8, 8, C, F) -> (4, dr, 4, dc, C, F) -> (4, 4, dr, dc, C, F)
    w4 = k8.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
    return w4.reshape(4, 4, 4 * c, f)


# ----------------------------------------------------------------- GoogLeNet
@dataclasses.dataclass
class GoogLeNet(ZooModel):
    """GoogLeNet / Inception-v1 (DL4J `zoo/model/GoogLeNet.java`), without
    the auxiliary classifier heads (DL4J omits them too)."""
    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (224, 224, 3)
    seed: int = 123

    def _inception(self, g, name, inp, c1, c3r, c3, c5r, c5, pp):
        g.add_layer(f"{name}_1x1",
                    ConvolutionLayer(n_out=c1, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="relu"), inp)
        g.add_layer(f"{name}_3x3r",
                    ConvolutionLayer(n_out=c3r, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="relu"), inp)
        g.add_layer(f"{name}_3x3",
                    ConvolutionLayer(n_out=c3, kernel=(3, 3),
                                     convolution_mode="same",
                                     activation="relu"), f"{name}_3x3r")
        g.add_layer(f"{name}_5x5r",
                    ConvolutionLayer(n_out=c5r, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="relu"), inp)
        g.add_layer(f"{name}_5x5",
                    ConvolutionLayer(n_out=c5, kernel=(5, 5),
                                     convolution_mode="same",
                                     activation="relu"), f"{name}_5x5r")
        g.add_layer(f"{name}_pool",
                    SubsamplingLayer(kernel=(3, 3), stride=(1, 1),
                                     convolution_mode="same"), inp)
        g.add_layer(f"{name}_poolproj",
                    ConvolutionLayer(n_out=pp, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="relu"), f"{name}_pool")
        g.add_vertex(f"{name}_out", MergeVertex(),
                     f"{name}_1x1", f"{name}_3x3", f"{name}_5x5",
                     f"{name}_poolproj")
        return f"{name}_out"

    def conf(self):
        g = (GraphBuilder(NeuralNetConfiguration.Builder()
                          .seed(self.seed)
                          .updater(Nesterovs(1e-2, momentum=0.9))
                          .weight_init("relu"))
             .add_inputs("input")
             .set_input_types(InputType.convolutional(*self.input_shape)))
        g.add_layer("stem_conv",
                    ConvolutionLayer(n_out=64, kernel=(7, 7), stride=(2, 2),
                                     convolution_mode="same",
                                     activation="relu"), "input")
        g.add_layer("stem_pool",
                    SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), "stem_conv")
        g.add_layer("stem_lrn", LocalResponseNormalization(), "stem_pool")
        g.add_layer("stem2_red",
                    ConvolutionLayer(n_out=64, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="relu"), "stem_lrn")
        g.add_layer("stem2_conv",
                    ConvolutionLayer(n_out=192, kernel=(3, 3),
                                     convolution_mode="same",
                                     activation="relu"), "stem2_red")
        g.add_layer("stem2_lrn", LocalResponseNormalization(), "stem2_conv")
        g.add_layer("stem2_pool",
                    SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                     convolution_mode="same"), "stem2_lrn")
        x = self._inception(g, "inc3a", "stem2_pool", 64, 96, 128, 16, 32, 32)
        x = self._inception(g, "inc3b", x, 128, 128, 192, 32, 96, 64)
        g.add_layer("pool3", SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                              convolution_mode="same"), x)
        x = self._inception(g, "inc4a", "pool3", 192, 96, 208, 16, 48, 64)
        x = self._inception(g, "inc4b", x, 160, 112, 224, 24, 64, 64)
        x = self._inception(g, "inc4c", x, 128, 128, 256, 24, 64, 64)
        x = self._inception(g, "inc4d", x, 112, 144, 288, 32, 64, 64)
        x = self._inception(g, "inc4e", x, 256, 160, 320, 32, 128, 128)
        g.add_layer("pool4", SubsamplingLayer(kernel=(3, 3), stride=(2, 2),
                                              convolution_mode="same"), x)
        x = self._inception(g, "inc5a", "pool4", 256, 160, 320, 32, 128, 128)
        x = self._inception(g, "inc5b", x, 384, 192, 384, 48, 128, 128)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("dropout", DropoutLayer(dropout=0.4), "avgpool")
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes, activation="softmax",
                                loss="mcxent"), "dropout")
        g.set_outputs("output")
        return g.build()


# ----------------------------------------------------------------- Darknet19
@dataclasses.dataclass
class Darknet19(ZooModel):
    """Darknet-19 classification backbone (DL4J `zoo/model/Darknet19.java`)."""
    num_classes: int = 1000
    input_shape: Tuple[int, int, int] = (224, 224, 3)
    seed: int = 123

    @staticmethod
    def _dn_conv(b, n_out, kernel):
        b.layer(ConvolutionLayer(n_out=n_out, kernel=kernel,
                                 convolution_mode="same",
                                 activation="identity", has_bias=False))
        b.layer(BatchNormalization())
        b.layer(ActivationLayer(activation="leakyrelu", alpha=0.1))

    def conf(self):
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(Nesterovs(1e-3, momentum=0.9))
             .weight_init("relu")
             .list())
        plan = [(32,), "M", (64,), "M", (128, 64, 128), "M",
                (256, 128, 256), "M", (512, 256, 512, 256, 512), "M",
                (1024, 512, 1024, 512, 1024)]
        for item in plan:
            if item == "M":
                b.layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            else:
                for i, n in enumerate(item):
                    k = (3, 3) if (len(item) == 1 or i % 2 == 0) else (1, 1)
                    self._dn_conv(b, n, k)
        b.layer(ConvolutionLayer(n_out=self.num_classes, kernel=(1, 1),
                                 convolution_mode="same",
                                 activation="identity"))
        b.layer(GlobalPoolingLayer(pooling_type="avg"))
        b.layer(OutputLayer(n_out=self.num_classes, activation="softmax",
                            loss="mcxent", n_in=self.num_classes))
        b.set_input_type(InputType.convolutional(*self.input_shape))
        return b.build()


# ---------------------------------------------------------------- YOLO family
def _yolo_backbone(g, prefix, inp, plan):
    x = inp
    for i, item in enumerate(plan):
        name = f"{prefix}{i}"
        if item == "M":
            g.add_layer(name, SubsamplingLayer(kernel=(2, 2), stride=(2, 2)),
                        x)
        else:
            n_out, k = item
            g.add_layer(f"{name}_conv",
                        ConvolutionLayer(n_out=n_out, kernel=(k, k),
                                         convolution_mode="same",
                                         activation="identity",
                                         has_bias=False), x)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
            g.add_layer(name, ActivationLayer(activation="leakyrelu",
                                              alpha=0.1), f"{name}_bn")
        x = name
    return x


@dataclasses.dataclass
class TinyYOLO(ZooModel):
    """Tiny YOLO v2 (DL4J `zoo/model/TinyYOLO.java`): Darknet-tiny backbone +
    Yolo2OutputLayer head with 5 anchor boxes on a 13x13 grid."""
    num_classes: int = 20
    input_shape: Tuple[int, int, int] = (416, 416, 3)
    seed: int = 123
    anchors: Tuple[Tuple[float, float], ...] = (
        (1.08, 1.19), (3.42, 4.41), (6.63, 11.38), (9.42, 5.11), (16.62, 10.52))

    def conf(self):
        from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
        g = (GraphBuilder(NeuralNetConfiguration.Builder()
                          .seed(self.seed)
                          .updater(Adam(1e-3))
                          .weight_init("relu"))
             .add_inputs("input")
             .set_input_types(InputType.convolutional(*self.input_shape)))
        plan = [(16, 3), "M", (32, 3), "M", (64, 3), "M", (128, 3), "M",
                (256, 3), "M", (512, 3), (1024, 3), (1024, 3)]
        x = _yolo_backbone(g, "b", "input", plan)
        n_b = len(self.anchors)
        g.add_layer("det",
                    ConvolutionLayer(n_out=n_b * (5 + self.num_classes),
                                     kernel=(1, 1), convolution_mode="same",
                                     activation="identity"), x)
        g.add_layer("yolo", Yolo2OutputLayer(anchors=self.anchors,
                                             n_classes=self.num_classes),
                    "det")
        g.set_outputs("yolo")
        return g.build()


@dataclasses.dataclass
class YOLO2(ZooModel):
    """YOLO v2 (DL4J `zoo/model/YOLO2.java`): Darknet-19 backbone with the
    passthrough route omitted in DL4J's published config too."""
    num_classes: int = 80
    input_shape: Tuple[int, int, int] = (608, 608, 3)
    seed: int = 123
    anchors: Tuple[Tuple[float, float], ...] = (
        (0.57273, 0.677385), (1.87446, 2.06253), (3.33843, 5.47434),
        (7.88282, 3.52778), (9.77052, 9.16828))

    def conf(self):
        from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
        g = (GraphBuilder(NeuralNetConfiguration.Builder()
                          .seed(self.seed)
                          .updater(Adam(1e-3))
                          .weight_init("relu"))
             .add_inputs("input")
             .set_input_types(InputType.convolutional(*self.input_shape)))
        plan = [(32, 3), "M", (64, 3), "M", (128, 3), (64, 1), (128, 3), "M",
                (256, 3), (128, 1), (256, 3), "M",
                (512, 3), (256, 1), (512, 3), (256, 1), (512, 3), "M",
                (1024, 3), (512, 1), (1024, 3), (512, 1), (1024, 3),
                (1024, 3), (1024, 3)]
        x = _yolo_backbone(g, "b", "input", plan)
        n_b = len(self.anchors)
        g.add_layer("det",
                    ConvolutionLayer(n_out=n_b * (5 + self.num_classes),
                                     kernel=(1, 1), convolution_mode="same",
                                     activation="identity"), x)
        g.add_layer("yolo", Yolo2OutputLayer(anchors=self.anchors,
                                             n_classes=self.num_classes),
                    "det")
        g.set_outputs("yolo")
        return g.build()


# -------------------------------------------------------- TextGenerationLSTM
@dataclasses.dataclass
class TextGenerationLSTM(ZooModel):
    """Char-level text generation LSTM (DL4J `zoo/model/TextGenerationLSTM.java`):
    two stacked LSTMs + RNN softmax head, truncated BPTT length 50."""
    total_unique_characters: int = 47
    max_length: int = 40
    units: int = 256
    seed: int = 123

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(Adam(1e-3))
                .grad_clip_norm(5.0)
                .list()
                .layer(LSTM(n_out=self.units, activation="tanh"))
                .layer(LSTM(n_out=self.units, activation="tanh"))
                .layer(RnnOutputLayer(n_out=self.total_unique_characters,
                                      activation="softmax", loss="mcxent"))
                .set_input_type(InputType.recurrent(
                    self.total_unique_characters, self.max_length))
                .backprop_type("tbptt", 50, 50)
                .build())


# ------------------------------------------------------- InceptionResNetV1
@dataclasses.dataclass
class InceptionResNetV1(ZooModel):
    """Inception-ResNet v1 (DL4J `zoo/model/InceptionResNetV1.java`), the
    FaceNet backbone. Reduced-depth faithful shape: stem + 5x block35 +
    reduction-A + 10x block17 + reduction-B + 5x block8 + avgpool + head."""
    num_classes: int = 1001
    input_shape: Tuple[int, int, int] = (160, 160, 3)
    seed: int = 123
    embedding_size: int = 128

    def _conv(self, g, name, inp, n_out, kernel, stride=(1, 1), pad="same"):
        g.add_layer(f"{name}_c",
                    ConvolutionLayer(n_out=n_out, kernel=kernel, stride=stride,
                                     convolution_mode=pad,
                                     activation="identity", has_bias=False),
                    inp)
        g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_c")
        g.add_layer(name, ActivationLayer(activation="relu"), f"{name}_bn")
        return name

    def _block35(self, g, name, inp, scale=0.17):
        b0 = self._conv(g, f"{name}_b0", inp, 32, (1, 1))
        b1 = self._conv(g, f"{name}_b1a", inp, 32, (1, 1))
        b1 = self._conv(g, f"{name}_b1b", b1, 32, (3, 3))
        b2 = self._conv(g, f"{name}_b2a", inp, 32, (1, 1))
        b2 = self._conv(g, f"{name}_b2b", b2, 32, (3, 3))
        b2 = self._conv(g, f"{name}_b2c", b2, 32, (3, 3))
        g.add_vertex(f"{name}_cat", MergeVertex(), b0, b1, b2)
        g.add_layer(f"{name}_up",
                    ConvolutionLayer(n_out=256, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="identity"), f"{name}_cat")
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), f"{name}_up")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        g.add_layer(name, ActivationLayer(activation="relu"), f"{name}_add")
        return name

    def _block17(self, g, name, inp, scale=0.10):
        b0 = self._conv(g, f"{name}_b0", inp, 128, (1, 1))
        b1 = self._conv(g, f"{name}_b1a", inp, 128, (1, 1))
        b1 = self._conv(g, f"{name}_b1b", b1, 128, (1, 7))
        b1 = self._conv(g, f"{name}_b1c", b1, 128, (7, 1))
        g.add_vertex(f"{name}_cat", MergeVertex(), b0, b1)
        g.add_layer(f"{name}_up",
                    ConvolutionLayer(n_out=896, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="identity"), f"{name}_cat")
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), f"{name}_up")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        g.add_layer(name, ActivationLayer(activation="relu"), f"{name}_add")
        return name

    def _block8(self, g, name, inp, scale=0.20, relu=True):
        b0 = self._conv(g, f"{name}_b0", inp, 192, (1, 1))
        b1 = self._conv(g, f"{name}_b1a", inp, 192, (1, 1))
        b1 = self._conv(g, f"{name}_b1b", b1, 192, (1, 3))
        b1 = self._conv(g, f"{name}_b1c", b1, 192, (3, 1))
        g.add_vertex(f"{name}_cat", MergeVertex(), b0, b1)
        g.add_layer(f"{name}_up",
                    ConvolutionLayer(n_out=1792, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="identity"), f"{name}_cat")
        g.add_vertex(f"{name}_scale", ScaleVertex(scale=scale), f"{name}_up")
        g.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), inp,
                     f"{name}_scale")
        if relu:
            g.add_layer(name, ActivationLayer(activation="relu"),
                        f"{name}_add")
            return name
        return f"{name}_add"

    def conf(self):
        g = (GraphBuilder(NeuralNetConfiguration.Builder()
                          .seed(self.seed)
                          .updater(Adam(1e-3))
                          .weight_init("relu"))
             .add_inputs("input")
             .set_input_types(InputType.convolutional(*self.input_shape)))
        x = self._conv(g, "stem1", "input", 32, (3, 3), (2, 2), "truncate")
        x = self._conv(g, "stem2", x, 32, (3, 3), (1, 1), "truncate")
        x = self._conv(g, "stem3", x, 64, (3, 3))
        g.add_layer("stem_pool",
                    SubsamplingLayer(kernel=(3, 3), stride=(2, 2)), x)
        x = self._conv(g, "stem4", "stem_pool", 80, (1, 1))
        x = self._conv(g, "stem5", x, 192, (3, 3), (1, 1), "truncate")
        x = self._conv(g, "stem6", x, 256, (3, 3), (2, 2), "truncate")
        for i in range(5):
            x = self._block35(g, f"b35_{i}", x)
        # reduction-A
        ra0 = self._conv(g, "redA_b0", x, 384, (3, 3), (2, 2), "truncate")
        ra1 = self._conv(g, "redA_b1a", x, 192, (1, 1))
        ra1 = self._conv(g, "redA_b1b", ra1, 192, (3, 3))
        ra1 = self._conv(g, "redA_b1c", ra1, 256, (3, 3), (2, 2), "truncate")
        g.add_layer("redA_pool",
                    SubsamplingLayer(kernel=(3, 3), stride=(2, 2)), x)
        g.add_vertex("redA", MergeVertex(), ra0, ra1, "redA_pool")
        x = "redA"
        for i in range(10):
            x = self._block17(g, f"b17_{i}", x)
        # reduction-B
        rb0 = self._conv(g, "redB_b0a", x, 256, (1, 1))
        rb0 = self._conv(g, "redB_b0b", rb0, 384, (3, 3), (2, 2), "truncate")
        rb1 = self._conv(g, "redB_b1a", x, 256, (1, 1))
        rb1 = self._conv(g, "redB_b1b", rb1, 256, (3, 3), (2, 2), "truncate")
        rb2 = self._conv(g, "redB_b2a", x, 256, (1, 1))
        rb2 = self._conv(g, "redB_b2b", rb2, 256, (3, 3))
        rb2 = self._conv(g, "redB_b2c", rb2, 256, (3, 3), (2, 2), "truncate")
        g.add_layer("redB_pool",
                    SubsamplingLayer(kernel=(3, 3), stride=(2, 2)), x)
        g.add_vertex("redB", MergeVertex(), rb0, rb1, rb2, "redB_pool")
        x = "redB"
        for i in range(5):
            x = self._block8(g, f"b8_{i}", x)
        g.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        g.add_layer("bottleneck", DenseLayer(n_out=self.embedding_size,
                                             activation="identity"), "avgpool")
        g.add_layer("output",
                    OutputLayer(n_out=self.num_classes, activation="softmax",
                                loss="mcxent"), "bottleneck")
        g.set_outputs("output")
        return g.build()


@dataclasses.dataclass
class FaceNetNN4Small2(InceptionResNetV1):
    """FaceNet (DL4J `zoo/model/FaceNetNN4Small2.java`): the embedding
    variant — same backbone, 128-d L2-normalized embedding head trained
    with center-loss in DL4J; here softmax + embedding bottleneck."""
    num_classes: int = 1001
    input_shape: Tuple[int, int, int] = (96, 96, 3)
    embedding_size: int = 128


# ---------------------------------------------------------------------- UNet
@dataclasses.dataclass
class UNet(ZooModel):
    """U-Net (DL4J `zoo/model/UNet.java`): encoder/decoder with skip merges,
    sigmoid pixel head."""
    num_classes: int = 1
    input_shape: Tuple[int, int, int] = (128, 128, 3)
    seed: int = 123

    def _double_conv(self, g, name, inp, n_out):
        g.add_layer(f"{name}_c1",
                    ConvolutionLayer(n_out=n_out, kernel=(3, 3),
                                     convolution_mode="same",
                                     activation="relu"), inp)
        g.add_layer(f"{name}_c2",
                    ConvolutionLayer(n_out=n_out, kernel=(3, 3),
                                     convolution_mode="same",
                                     activation="relu"), f"{name}_c1")
        return f"{name}_c2"

    def conf(self):
        from deeplearning4j_tpu.nn.layers import CnnLossLayer
        g = (GraphBuilder(NeuralNetConfiguration.Builder()
                          .seed(self.seed)
                          .updater(Adam(1e-4))
                          .weight_init("relu"))
             .add_inputs("input")
             .set_input_types(InputType.convolutional(*self.input_shape)))
        widths = (64, 128, 256, 512)
        skips = []
        x = "input"
        for i, w in enumerate(widths):
            x = self._double_conv(g, f"enc{i}", x, w)
            skips.append(x)
            g.add_layer(f"down{i}", SubsamplingLayer(kernel=(2, 2),
                                                     stride=(2, 2)), x)
            x = f"down{i}"
        x = self._double_conv(g, "mid", x, 1024)
        for i, w in reversed(list(enumerate(widths))):
            g.add_layer(f"up{i}", Upsampling2D(size=(2, 2)), x)
            g.add_layer(f"upc{i}",
                        ConvolutionLayer(n_out=w, kernel=(2, 2),
                                         convolution_mode="same",
                                         activation="relu"), f"up{i}")
            g.add_vertex(f"cat{i}", MergeVertex(), skips[i], f"upc{i}")
            x = self._double_conv(g, f"dec{i}", f"cat{i}", w)
        g.add_layer("head",
                    ConvolutionLayer(n_out=self.num_classes, kernel=(1, 1),
                                     convolution_mode="same",
                                     activation="sigmoid"), x)
        g.add_layer("loss", CnnLossLayer(loss="xent", activation="identity"),
                    "head")
        g.set_outputs("loss")
        return g.build()


# ------------------------------------------------------------ name registry
def zoo_models() -> dict:
    """Name -> ZooModel subclass map (every concrete arch in this module
    plus the transformer LM family), the resolver behind `zoo:<Name>`
    servable sources and CLI flags."""
    from deeplearning4j_tpu.models import transformer
    out = {}
    for mod_globals in (globals(), vars(transformer)):
        for obj in mod_globals.values():
            if isinstance(obj, type) and issubclass(obj, ZooModel) \
                    and obj is not ZooModel:
                out[obj.__name__] = obj
    return out


def model_by_name(name: str, **overrides) -> ZooModel:
    """Instantiate a zoo architecture by (case-insensitive) class name,
    with dataclass field overrides (num_classes=, input_shape=, seed=).
    Raises KeyError listing the known names for a typo'd arch."""
    models = zoo_models()
    by_lower = {k.lower(): v for k, v in models.items()}
    cls = models.get(name) or by_lower.get(name.lower())
    if cls is None:
        raise KeyError(f"unknown zoo model {name!r}; available: "
                       f"{', '.join(sorted(models))}")
    return cls(**overrides)
