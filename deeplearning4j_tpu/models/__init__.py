"""Model zoo — canonical architectures (DL4J deeplearning4j-zoo parity).

Reference: /root/reference/deeplearning4j-zoo/src/main/java/org/deeplearning4j/zoo/
(`ZooModel.java`, `model/*.java`). Architectures are re-expressed TPU-first:
NHWC layouts, bf16-friendly compute, builders produce jit-compiled networks.
"""
from deeplearning4j_tpu.models.zoo import (
    ZooModel,
    LeNet,
    SimpleCNN,
    AlexNet,
    VGG16,
    VGG19,
    ResNet50,
    GoogLeNet,
    Darknet19,
    TinyYOLO,
    YOLO2,
    TextGenerationLSTM,
    InceptionResNetV1,
    FaceNetNN4Small2,
    UNet,
    model_by_name,
    zoo_models,
)
from deeplearning4j_tpu.models.transformer import TransformerLM, TransformerLMMoE

__all__ = [
    "ZooModel", "LeNet", "SimpleCNN", "AlexNet", "VGG16", "VGG19",
    "ResNet50", "GoogLeNet", "Darknet19", "TinyYOLO", "YOLO2",
    "TextGenerationLSTM", "InceptionResNetV1", "FaceNetNN4Small2", "UNet",
    "TransformerLM", "TransformerLMMoE", "model_by_name", "zoo_models",
]
