"""Native host kernels: build-on-demand C++ via g++ + ctypes.

The reference's host hot paths are out-of-tree C++ consumed over JNI
(SURVEY.md §2.1: libnd4j compression ops, AggregateSkipGram HogWild
aggregates). This package is the analog: `src/dl4jtpu_native.cpp` compiles
once into a cached shared library; if no toolchain is present everything
degrades to the pure JAX/numpy implementations (the callers check
`available()`), so the framework never hard-requires a compiler.
"""
from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_SRCS = [os.path.join(_SRC_DIR, "dl4jtpu_native.cpp"),
         os.path.join(_SRC_DIR, "ndarray_ops.cpp"),
         os.path.join(_SRC_DIR, "sptree.cpp"),
         os.path.join(_SRC_DIR, "csv.cpp"),
         os.path.join(_SRC_DIR, "tokenizer.cpp")]
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _cache_dir() -> str:
    from deeplearning4j_tpu.util.env import env_str
    d = env_str(
        "DL4J_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache",
                     "deeplearning4j_tpu"))
    os.makedirs(d, exist_ok=True)
    return d


def _build() -> Optional[ctypes.CDLL]:
    h = hashlib.sha256()
    for path in _SRCS:
        with open(path, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    so_path = os.path.join(_cache_dir(), f"dl4jtpu_native-{tag}.so")
    if not os.path.exists(so_path):
        base = ["g++", "-std=c++17", "-O3", "-shared", "-fPIC",
                "-march=native", *_SRCS, "-o"]
        tmp = so_path + f".tmp{os.getpid()}"
        for extra in (["-fopenmp"], []):   # OpenMP if present, else serial
            cmd = base[:-1] + extra + ["-o", tmp]
            try:
                r = subprocess.run(cmd, capture_output=True, timeout=120)
            except (OSError, subprocess.TimeoutExpired) as e:
                log.warning("native build failed to run g++: %s", e)
                return None
            if r.returncode == 0:
                os.replace(tmp, so_path)
                break
        else:
            log.warning("native build failed:\n%s",
                        r.stderr.decode()[-1000:])
            return None
    lib = ctypes.CDLL(so_path)
    lib.threshold_encode_f32.restype = ctypes.c_int64
    lib.threshold_encode_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float)]
    lib.decode_accumulate_f32.restype = None
    lib.decode_accumulate_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64]
    lib.sg_ns_train.restype = ctypes.c_double
    lib.sg_ns_train.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.c_float, ctypes.c_float, ctypes.c_int64, ctypes.c_uint64,
        ctypes.c_int32]
    lib.native_abi_version.restype = ctypes.c_int32
    if lib.native_abi_version() != 1:
        log.warning("native ABI mismatch")
        return None
    _declare_ndarray_ops(lib)
    return lib


def _declare_ndarray_ops(lib: ctypes.CDLL) -> None:
    """ctypes prototypes for the INDArray-contract host kernels
    (src/ndarray_ops.cpp)."""
    f32p = ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    f32, u64 = ctypes.c_float, ctypes.c_uint64
    lib.dot_f32.restype = f32
    lib.dot_f32.argtypes = [f32p, f32p, i64]
    lib.axpy_f32.restype = None
    lib.axpy_f32.argtypes = [f32, f32p, f32p, i64]
    lib.nrm2_f32.restype = f32
    lib.nrm2_f32.argtypes = [f32p, i64]
    lib.gemm_f32.restype = None
    lib.gemm_f32.argtypes = [i32, i32, i64, i64, i64, f32, f32p, f32p,
                             f32, f32p]
    lib.transform_f32.restype = None
    lib.transform_f32.argtypes = [i32, f32p, i64, f32, f32p]
    lib.binary_f32.restype = None
    lib.binary_f32.argtypes = [i32, f32p, f32p, i64, f32p]
    lib.broadcast_row_f32.restype = None
    lib.broadcast_row_f32.argtypes = [i32, f32p, i64, i64, f32p, f32p]
    lib.reduce_f32.restype = None
    lib.reduce_f32.argtypes = [i32, f32p, i64, i64, i32, f32p]
    lib.im2col_f32.restype = None
    lib.im2col_f32.argtypes = [f32p] + [i64] * 9 + [f32p]
    lib.col2im_f32.restype = None
    lib.col2im_f32.argtypes = [f32p] + [i64] * 9 + [f32p]
    lib.random_uniform_f32.restype = None
    lib.random_uniform_f32.argtypes = [u64, i64, f32, f32, f32p]
    lib.random_gaussian_f32.restype = None
    lib.random_gaussian_f32.argtypes = [u64, i64, f32, f32, f32p]
    lib.pairwise_sqdist_f32.restype = None
    lib.pairwise_sqdist_f32.argtypes = [f32p, i64, f32p, i64, i64, f32p]
    lib.bh_repulsion_f32.restype = ctypes.c_double
    lib.bh_repulsion_f32.argtypes = [f32p, i64, i32, f32, f32p,
                                     ctypes.POINTER(i64)]
    lib.csv_parse_f32.restype = i64
    lib.csv_parse_f32.argtypes = [ctypes.c_char_p, i64, ctypes.c_char,
                                  i64, f32p, i64, ctypes.POINTER(i64)]
    lib.scale_u8_f32.restype = None
    lib.scale_u8_f32.argtypes = [u8p, i64, f32, f32, f32p]
    # batch tokenizer (src/tokenizer.cpp)
    i64p = ctypes.POINTER(i64)
    i32p = ctypes.POINTER(i32)
    lib.dl4j_vocab_create.restype = ctypes.c_void_p
    lib.dl4j_vocab_create.argtypes = [ctypes.c_char_p, i64p, i64]
    lib.dl4j_vocab_free.restype = None
    lib.dl4j_vocab_free.argtypes = [ctypes.c_void_p]
    lib.dl4j_tokenize_encode.restype = i64
    lib.dl4j_tokenize_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, i64, i32, i32,
        i32p, i64, i64p, i64, i64p]
    lib.dl4j_count_tokens.restype = ctypes.c_void_p
    lib.dl4j_count_tokens.argtypes = [ctypes.c_char_p, i64, i32]
    lib.dl4j_counts_size.restype = i64
    lib.dl4j_counts_size.argtypes = [ctypes.c_void_p]
    lib.dl4j_counts_blob_len.restype = i64
    lib.dl4j_counts_blob_len.argtypes = [ctypes.c_void_p]
    lib.dl4j_counts_export.restype = None
    lib.dl4j_counts_export.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       i64p, i64p]
    lib.dl4j_counts_free.restype = None
    lib.dl4j_counts_free.argtypes = [ctypes.c_void_p]


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is None and not _build_failed:
        _lib = _build()
        if _lib is None:
            _build_failed = True
    return _lib


def available() -> bool:
    return get_lib() is not None


def _require_lib() -> ctypes.CDLL:
    lib = get_lib()
    if lib is None:
        raise RuntimeError(
            "native backend unavailable: the g++ build failed or no "
            "toolchain is present (check the 'native build failed' log); "
            "use the jax/device backends instead")
    return lib


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def threshold_encode(grad: np.ndarray, threshold: float, cap: int
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host codec: exact-magnitude sparse encode. Returns (idx int32[m],
    vals f32[m], residual f32 like grad) with m <= cap — the native twin of
    encoding.threshold_encode_values (no -1 padding: host buffers are
    dynamic)."""
    lib = _require_lib()
    g = np.ascontiguousarray(np.asarray(grad, np.float32).reshape(-1))
    n = g.size
    cap = int(min(max(cap, 0), n))
    idx = np.empty(cap, np.int32)
    vals = np.empty(cap, np.float32)
    residual = np.empty(n, np.float32)
    m = lib.threshold_encode_f32(_fptr(g), n, ctypes.c_float(threshold),
                                 cap, _i32ptr(idx), _fptr(vals),
                                 _fptr(residual))
    return idx[:m].copy(), vals[:m].copy(), residual.reshape(grad.shape)


def decode_accumulate(dense: np.ndarray, idx: np.ndarray,
                      vals: np.ndarray) -> np.ndarray:
    lib = _require_lib()
    d = np.ascontiguousarray(np.asarray(dense, np.float32))
    lib.decode_accumulate_f32(
        _fptr(d), d.size, _i32ptr(np.ascontiguousarray(idx, np.int32)),
        _fptr(np.ascontiguousarray(vals, np.float32)), int(len(idx)))
    return d


def sg_ns_train(syn0: np.ndarray, syn1neg: np.ndarray, corpus: np.ndarray,
                offsets: np.ndarray, window: int, negative: int,
                table: np.ndarray, lr_start: float, lr_min: float,
                total_words: int, seed: int = 0,
                n_threads: int = 0) -> float:
    """HogWild skip-gram/negative-sampling epoch IN PLACE on syn0/syn1neg.
    Returns mean pair loss (AggregateSkipGram analog)."""
    lib = _require_lib()
    for name, a in (("syn0", syn0), ("syn1neg", syn1neg)):
        if not (isinstance(a, np.ndarray) and a.dtype == np.float32
                and a.flags["C_CONTIGUOUS"]):
            # a silent ascontiguousarray copy would discard the in-place
            # updates — demand the right layout instead
            raise ValueError(f"{name} must be C-contiguous float32")
    corpus = np.ascontiguousarray(corpus, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    table = np.ascontiguousarray(table, np.int32)
    loss = lib.sg_ns_train(
        _fptr(syn0), _fptr(syn1neg), syn0.shape[0], syn0.shape[1],
        _i32ptr(corpus),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(offsets) - 1, window, negative, _i32ptr(table), table.size,
        ctypes.c_float(lr_start), ctypes.c_float(lr_min),
        int(total_words), ctypes.c_uint64(seed), int(n_threads))
    return float(loss)
