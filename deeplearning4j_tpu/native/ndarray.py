"""HostNDArray — the INDArray op contract on host buffers, C++-backed.

SURVEY.md §2.1 records the op surface the reference consumes from its
native tensor layer (INDArray 266 imports, Nd4j factory 107, Transforms
16, gemm at LSTMHelpers.java:212/522/616, im2col at
ConvolutionLayer.java:215). On TPU the device half of that contract is
XLA (SURVEY §7 by-design collapse); this module is the host half — the
`nd4j-native` analog used by host-side subsystems (clustering distance
kernels, dataset ETL, codec paths) and as a toolchain-free numpy
fallback when g++ is unavailable.

Every op dispatches to src/ndarray_ops.cpp via ctypes when
`native.available()`, else to the numpy twin — same results either way
(tests assert backend equivalence, the ValidateCudnnLSTM pattern of
SURVEY §4).
"""
from __future__ import annotations

import ctypes
from typing import Optional, Tuple, Union

import numpy as np

from . import available, get_lib

_TRANSFORM = {"exp": 0, "log": 1, "tanh": 2, "sigmoid": 3, "relu": 4,
              "sqrt": 5, "abs": 6, "neg": 7, "square": 8, "add_scalar": 9,
              "mul_scalar": 10, "pow_scalar": 11, "clip_min": 12,
              "clip_max": 13, "sign": 14, "reciprocal": 15}
_BINARY = {"add": 0, "sub": 1, "mul": 2, "div": 3, "max": 4, "min": 5}
_REDUCE = {"sum": 0, "mean": 1, "max": 2, "min": 3, "argmax": 4,
           "norm2": 5}


def _f32(a) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(a, np.float32))


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class HostNDArray:
    """A host f32 tensor carrying the INDArray-style fluent op surface."""

    __array_priority__ = 100    # our __r*__ ops win over np scalars/arrays

    def __init__(self, data):
        self.data = _f32(data.data if isinstance(data, HostNDArray)
                         else data)

    # ---- factory (Nd4j.* analogs) ------------------------------------
    @staticmethod
    def zeros(*shape: int) -> "HostNDArray":
        return HostNDArray(np.zeros(shape, np.float32))

    @staticmethod
    def ones(*shape: int) -> "HostNDArray":
        return HostNDArray(np.ones(shape, np.float32))

    @staticmethod
    def rand(*shape: int, seed: int = 0, lo: float = 0.0,
             hi: float = 1.0) -> "HostNDArray":
        n = int(np.prod(shape)) if shape else 1
        out = np.empty(n, np.float32)
        if available():
            get_lib().random_uniform_f32(
                ctypes.c_uint64(seed), n, ctypes.c_float(lo),
                ctypes.c_float(hi), _ptr(out))
        else:
            out[:] = np.random.RandomState(seed & 0x7FFFFFFF).uniform(
                lo, hi, n).astype(np.float32)
        return HostNDArray(out.reshape(shape))

    @staticmethod
    def randn(*shape: int, seed: int = 0, mean: float = 0.0,
              std: float = 1.0) -> "HostNDArray":
        n = int(np.prod(shape)) if shape else 1
        out = np.empty(n, np.float32)
        if available():
            get_lib().random_gaussian_f32(
                ctypes.c_uint64(seed), n, ctypes.c_float(mean),
                ctypes.c_float(std), _ptr(out))
        else:
            out[:] = np.random.RandomState(seed & 0x7FFFFFFF).normal(
                mean, std, n).astype(np.float32)
        return HostNDArray(out.reshape(shape))

    # ---- shape ------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return self.data.shape

    def reshape(self, *shape: int) -> "HostNDArray":
        return HostNDArray(self.data.reshape(shape))

    def transpose(self) -> "HostNDArray":
        return HostNDArray(np.ascontiguousarray(self.data.T))

    def ravel(self) -> "HostNDArray":
        return HostNDArray(self.data.reshape(-1))

    def numpy(self) -> np.ndarray:
        return self.data

    def __repr__(self) -> str:
        return f"HostNDArray{self.shape}\n{self.data!r}"

    # ---- BLAS -------------------------------------------------------
    def mmul(self, other: "HostNDArray", transpose_a: bool = False,
             transpose_b: bool = False, alpha: float = 1.0) -> "HostNDArray":
        """gemm: op(self) @ op(other) (Nd4j.gemm,
        LSTMHelpers.java:212)."""
        a, b = self.data, _as_np(other)
        if a.ndim != 2 or b.ndim != 2:
            raise ValueError("mmul expects rank-2 operands")
        m = a.shape[1] if transpose_a else a.shape[0]
        ka = a.shape[0] if transpose_a else a.shape[1]
        kb = b.shape[1] if transpose_b else b.shape[0]
        n = b.shape[0] if transpose_b else b.shape[1]
        if ka != kb:
            raise ValueError(f"mmul shape mismatch: {a.shape} x {b.shape}")
        if available():
            out = np.zeros((m, n), np.float32)
            get_lib().gemm_f32(int(transpose_a), int(transpose_b), m, n,
                               ka, ctypes.c_float(alpha), _ptr(a), _ptr(b),
                               ctypes.c_float(0.0), _ptr(out))
        else:
            out = alpha * ((a.T if transpose_a else a)
                           @ (b.T if transpose_b else b))
        return HostNDArray(out)

    def dot(self, other: "HostNDArray") -> float:
        a, b = self.data.reshape(-1), _as_np(other).reshape(-1)
        if a.size != b.size:
            raise ValueError(f"dot length mismatch: {a.size} vs {b.size}")
        if available():
            return float(get_lib().dot_f32(_ptr(a), _ptr(b), a.size))
        return float(a @ b)

    def norm2(self) -> float:
        a = self.data.reshape(-1)
        if available():
            return float(get_lib().nrm2_f32(_ptr(a), a.size))
        return float(np.linalg.norm(a))

    def axpy(self, alpha: float, y: "HostNDArray") -> "HostNDArray":
        """y += alpha * self, in place on y's buffer."""
        a, yd = self.data.reshape(-1), _as_np(y).reshape(-1)
        if a.size != yd.size:
            raise ValueError(
                f"axpy length mismatch: {a.size} vs {yd.size}")
        if available():
            get_lib().axpy_f32(ctypes.c_float(alpha), _ptr(a), _ptr(yd),
                               a.size)
        else:
            yd += alpha * a
        return y if isinstance(y, HostNDArray) else HostNDArray(yd)

    # ---- elementwise transforms (Transforms.* analogs) ---------------
    def _transform(self, name: str, arg: float = 0.0) -> "HostNDArray":
        x = self.data.reshape(-1)
        if available():
            out = np.empty_like(x)
            get_lib().transform_f32(_TRANSFORM[name], _ptr(x), x.size,
                                    ctypes.c_float(arg), _ptr(out))
        else:
            out = _np_transform(name, x, arg)
        return HostNDArray(out.reshape(self.shape))

    def exp(self): return self._transform("exp")
    def log(self): return self._transform("log")
    def tanh(self): return self._transform("tanh")
    def sigmoid(self): return self._transform("sigmoid")
    def relu(self): return self._transform("relu")
    def sqrt(self): return self._transform("sqrt")
    def abs(self): return self._transform("abs")
    def square(self): return self._transform("square")
    def sign(self): return self._transform("sign")
    def reciprocal(self): return self._transform("reciprocal")

    def clip(self, lo: float, hi: float) -> "HostNDArray":
        return self._transform("clip_min", lo)._transform("clip_max", hi)

    # ---- arithmetic ---------------------------------------------------
    def _binary(self, name: str, other) -> "HostNDArray":
        if np.isscalar(other):
            if name == "add":
                return self._transform("add_scalar", float(other))
            if name == "mul":
                return self._transform("mul_scalar", float(other))
            if name == "sub":
                return self._transform("add_scalar", -float(other))
            if name == "div":
                with np.errstate(divide="ignore"):  # 0 → ±inf, as elementwise
                    inv = float(np.float64(1.0) / np.float64(other))
                return self._transform("mul_scalar", inv)
            other = np.full_like(self.data, other)
        b = _as_np(other)
        if b.shape == self.shape:
            x = self.data.reshape(-1)
            bf = b.reshape(-1)
            if available():
                out = np.empty_like(x)
                get_lib().binary_f32(_BINARY[name], _ptr(x), _ptr(bf),
                                     x.size, _ptr(out))
            else:
                out = _np_binary(name, x, bf)
            return HostNDArray(out.reshape(self.shape))
        # row-vector broadcast (addiRowVector family)
        if self.data.ndim >= 1 and b.ndim == 1 \
                and self.shape[-1] == b.shape[0]:
            return self.broadcast_row(name, b)
        return HostNDArray(_np_binary(name, self.data, b))

    def broadcast_row(self, name: str, vec) -> "HostNDArray":
        v = _as_np(vec).reshape(-1)
        rows = int(np.prod(self.shape[:-1])) if self.data.ndim > 1 else 1
        cols = self.shape[-1]
        x = self.data.reshape(rows, cols)
        if available():
            out = np.empty_like(x)
            get_lib().broadcast_row_f32(_BINARY[name], _ptr(x), rows, cols,
                                        _ptr(v), _ptr(out))
        else:
            out = _np_binary(name, x, v[None, :])
        return HostNDArray(out.reshape(self.shape))

    def __add__(self, o): return self._binary("add", o)
    def __radd__(self, o): return self._binary("add", o)
    def __sub__(self, o): return self._binary("sub", o)
    def __rsub__(self, o): return self.__neg__()._binary("add", o)
    def __mul__(self, o): return self._binary("mul", o)
    def __rmul__(self, o): return self._binary("mul", o)
    def __truediv__(self, o): return self._binary("div", o)
    def __rtruediv__(self, o):
        num = np.full_like(self.data, o) if np.isscalar(o) else _as_np(o)
        return HostNDArray(num)._binary("div", self)
    def __neg__(self): return self._transform("neg")

    def maximum(self, o): return self._binary("max", o)
    def minimum(self, o): return self._binary("min", o)

    # ---- reductions ----------------------------------------------------
    def _reduce(self, name: str, axis: Optional[int]) \
            -> Union[float, "HostNDArray"]:
        if axis is None:
            if name == "argmax" and self.data.size == 0:
                raise ValueError("argmax of an empty array")
            flat = self.data.reshape(1, -1)
            out = np.empty(1, np.float32)
            if available():
                get_lib().reduce_f32(_REDUCE[name], _ptr(flat), 1,
                                     flat.shape[1], 1, _ptr(out))
            else:
                out[0] = _np_reduce(name, flat[0])
            return float(out[0])
        if self.data.ndim != 2:
            raise ValueError("axis reductions expect rank 2 (reshape first)")
        if axis == -1:
            axis = 1
        if axis not in (0, 1):
            raise ValueError(f"axis must be 0, 1 or -1, got {axis}")
        rows, cols = self.shape
        if name == "argmax" and (cols if axis == 1 else rows) == 0:
            raise ValueError("argmax of an empty array")
        out = np.empty(rows if axis == 1 else cols, np.float32)
        if available():
            get_lib().reduce_f32(_REDUCE[name], _ptr(self.data), rows,
                                 cols, axis, _ptr(out))
        else:
            out[:] = _np_reduce(name, self.data, axis)
        return HostNDArray(out)

    def sum(self, axis=None): return self._reduce("sum", axis)
    def mean(self, axis=None): return self._reduce("mean", axis)
    def max(self, axis=None): return self._reduce("max", axis)
    def min(self, axis=None): return self._reduce("min", axis)

    def argmax(self, axis=1) -> np.ndarray:
        r = self._reduce("argmax", axis)
        if isinstance(r, HostNDArray):
            return r.data.astype(np.int64)
        return np.int64(r)


def _as_np(x) -> np.ndarray:
    return x.data if isinstance(x, HostNDArray) else _f32(x)


def _np_transform(name: str, x: np.ndarray, arg: float) -> np.ndarray:
    f = {"exp": np.exp, "log": np.log, "tanh": np.tanh,
         "sigmoid": lambda v: 1.0 / (1.0 + np.exp(-v)),
         "relu": lambda v: np.maximum(v, 0), "sqrt": np.sqrt,
         "abs": np.abs, "neg": np.negative, "square": np.square,
         "add_scalar": lambda v: v + arg, "mul_scalar": lambda v: v * arg,
         "pow_scalar": lambda v: np.power(v, arg),
         "clip_min": lambda v: np.maximum(v, arg),
         "clip_max": lambda v: np.minimum(v, arg), "sign": np.sign,
         "reciprocal": lambda v: 1.0 / v}[name]
    return f(x).astype(np.float32)


def _np_binary(name: str, a, b) -> np.ndarray:
    f = {"add": np.add, "sub": np.subtract, "mul": np.multiply,
         "div": np.divide, "max": np.maximum, "min": np.minimum}[name]
    return f(a, b).astype(np.float32)


def _np_reduce(name: str, x: np.ndarray, axis=None):
    reduced = x.shape[axis] if axis is not None and x.ndim > 1 else x.size
    if reduced == 0:    # match native: sum of empty = 0, rest = NaN
        shape = () if axis is None or x.ndim <= 1 else \
            (x.shape[1 - axis],)
        return np.full(shape, 0.0 if name == "sum" else np.nan, np.float32)
    f = {"sum": np.sum, "mean": np.mean, "max": np.max, "min": np.min,
         "argmax": np.argmax, "norm2": lambda v, axis=None:
         np.sqrt(np.sum(np.square(v), axis=axis))}[name]
    return np.asarray(f(x, axis=axis) if x.ndim > 1 else f(x),
                      np.float32)


# ---- free functions on raw numpy (hot paths for other subsystems) -----

def im2col(img: np.ndarray, kh: int, kw: int, sh: int = 1, sw: int = 1,
           ph: int = 0, pw: int = 0) -> np.ndarray:
    """NCHW im2col ([C,H,W] → [C*kh*kw, oh*ow]); the
    ConvolutionLayer.java:215 host contract."""
    img = _f32(img)
    C, H, W = img.shape
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    out = np.empty((C * kh * kw, oh * ow), np.float32)
    if available():
        get_lib().im2col_f32(_ptr(img), C, H, W, kh, kw, sh, sw, ph, pw,
                             _ptr(out))
        return out
    padded = np.pad(img, ((0, 0), (ph, ph), (pw, pw)))
    k = 0
    for c in range(C):
        for ki in range(kh):
            for kj in range(kw):
                out[k] = padded[c, ki:ki + oh * sh:sh,
                                kj:kj + ow * sw:sw].reshape(-1)
                k += 1
    return out


def col2im(cols: np.ndarray, C: int, H: int, W: int, kh: int, kw: int,
           sh: int = 1, sw: int = 1, ph: int = 0, pw: int = 0
           ) -> np.ndarray:
    """Adjoint of im2col (gradient scatter-add back to the image)."""
    cols = _f32(cols)
    out = np.zeros((C, H, W), np.float32)
    if available():
        get_lib().col2im_f32(_ptr(cols), C, H, W, kh, kw, sh, sw, ph, pw,
                             _ptr(out))
        return out
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    padded = np.zeros((C, H + 2 * ph, W + 2 * pw), np.float32)
    k = 0
    for c in range(C):
        for ki in range(kh):
            for kj in range(kw):
                padded[c, ki:ki + oh * sh:sh, kj:kj + ow * sw:sw] += \
                    cols[k].reshape(oh, ow)
                k += 1
    return padded[:, ph:H + ph, pw:W + pw]


def pairwise_sqdist(X: np.ndarray, Q: np.ndarray) -> np.ndarray:
    """||X[i]-Q[j]||² for all pairs — the clustering/KNN host hot loop."""
    X, Q = _f32(X), _f32(Q)
    n, d = X.shape
    m = Q.shape[0]
    if available():
        out = np.empty((n, m), np.float32)
        get_lib().pairwise_sqdist_f32(_ptr(X), n, _ptr(Q), m, d, _ptr(out))
        return out
    # the expansion can round slightly negative for x≈q; clamp so callers
    # that feed these into probabilities/sqrt stay well-defined
    return np.maximum(np.sum(X * X, 1)[:, None] - 2.0 * (X @ Q.T)
                      + np.sum(Q * Q, 1)[None, :], 0.0).astype(np.float32)


def scale_u8(src: np.ndarray, scale: float, shift: float = 0.0
             ) -> np.ndarray:
    """u8 → f32 * scale + shift: byte-image ETL (fetcher normalization)."""
    src = np.ascontiguousarray(src, np.uint8)
    if available():
        out = np.empty(src.shape, np.float32)
        get_lib().scale_u8_f32(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), src.size,
            ctypes.c_float(scale), ctypes.c_float(shift),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out
    return src.astype(np.float32) * scale + shift
