// dl4jtpu_native — host-side native kernels.
//
// The reference keeps its host hot loops in native code (SURVEY.md §2.1):
// ND4J's C++ threshold/bitmap compression ops (consumed via
// Nd4j.getExecutioner().thresholdEncode, EncodingHandler.java:136-178) and
// the HogWild AggregateSkipGram/CBOW aggregates behind
// SkipGram.iterateSample (SkipGram.java:224-272). This module is their
// TPU-framework equivalent: the DCN-path gradient codec and the lock-free
// multithreaded skip-gram trainer run here; TPU compute stays in XLA.
//
// Built on demand with g++ -O3 (-fopenmp when available) — see
// deeplearning4j_tpu/native/__init__.py; every entry point is plain C ABI
// for ctypes.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// --------------------------------------------------------------- codec ----
// Sparse exact-magnitude threshold encoding (the host twin of
// encoding.threshold_encode_values): selects the top-`cap` elements with
// |g| >= threshold, writes (idx, val) pairs sorted by index, and the
// residual g - decoded. Returns the number of sent elements.
int64_t threshold_encode_f32(const float* grad, int64_t n, float threshold,
                             int64_t cap, int32_t* idx_out, float* val_out,
                             float* residual_out) {
    std::vector<int64_t> over;
    over.reserve(1024);
    for (int64_t i = 0; i < n; ++i) {
        if (std::fabs(grad[i]) >= threshold) over.push_back(i);
    }
    if ((int64_t)over.size() > cap) {
        std::nth_element(over.begin(), over.begin() + cap, over.end(),
                         [&](int64_t a, int64_t b) {
                             return std::fabs(grad[a]) > std::fabs(grad[b]);
                         });
        over.resize(cap);
    }
    std::sort(over.begin(), over.end());
    std::memcpy(residual_out, grad, sizeof(float) * (size_t)n);
    int64_t m = (int64_t)over.size();
    for (int64_t j = 0; j < m; ++j) {
        int64_t i = over[j];
        idx_out[j] = (int32_t)i;
        val_out[j] = grad[i];
        residual_out[i] = 0.0f;
    }
    return m;
}

// dense += scatter(idx, vals)
void decode_accumulate_f32(float* dense, int64_t n, const int32_t* idx,
                           const float* vals, int64_t m) {
    for (int64_t j = 0; j < m; ++j) {
        int32_t i = idx[j];
        if (i >= 0 && i < n) dense[i] += vals[j];
    }
}

// ------------------------------------------------------------- word2vec ----
// HogWild skip-gram + negative sampling over a flat id corpus.
// corpus: concatenated sentence ids; offsets[s]..offsets[s+1] delimit
// sentence s (n_sents+1 offsets). table: negative-sampling table of word
// ids (classic word2vec unigram^0.75 expansion). Threads race on
// syn0/syn1neg without locks — the HogWild contract the reference's
// AggregateSkipGram relies on too. Linear lr decay by processed-word
// count. Returns mean pair loss.
static inline uint64_t next_rand(uint64_t* s) {
    *s = *s * 6364136223846793005ULL + 1442695040888963407ULL;
    return *s;
}

static inline float fast_sigmoid(float x) {
    if (x > 8.0f) return 1.0f;
    if (x < -8.0f) return 0.0f;
    return 1.0f / (1.0f + std::exp(-x));
}

double sg_ns_train(float* syn0, float* syn1neg, int64_t vocab, int64_t dim,
                   const int32_t* corpus, const int64_t* offsets,
                   int64_t n_sents, int32_t window, int32_t negative,
                   const int32_t* table, int64_t table_size,
                   float lr_start, float lr_min, int64_t total_words,
                   uint64_t seed, int32_t n_threads) {
    std::atomic<int64_t> word_counter(0);
    double loss_sum = 0.0;
    int64_t pair_count = 0;
#ifdef _OPENMP
    if (n_threads > 0) omp_set_num_threads(n_threads);
#pragma omp parallel reduction(+ : loss_sum, pair_count)
#endif
    {
#ifdef _OPENMP
        int tid = omp_get_thread_num();
        int nth = omp_get_num_threads();
#else
        int tid = 0, nth = 1;
        (void)n_threads;
#endif
        uint64_t rng = seed + 0x9E3779B97F4A7C15ULL * (uint64_t)(tid + 1);
        std::vector<float> neu1e((size_t)dim);
        for (int64_t s = tid; s < n_sents; s += nth) {
            int64_t beg = offsets[s], end = offsets[s + 1];
            for (int64_t pos = beg; pos < end; ++pos) {
                int64_t seen = word_counter.fetch_add(1);
                float frac = total_words > 0
                                 ? (float)seen / (float)total_words
                                 : 0.0f;
                float lr = lr_start * (1.0f - frac);
                if (lr < lr_min) lr = lr_min;
                int32_t center = corpus[pos];
                int32_t b = (int32_t)(next_rand(&rng) % (uint64_t)window);
                for (int64_t j = pos - window + b; j <= pos + window - b;
                     ++j) {
                    if (j == pos || j < beg || j >= end) continue;
                    int32_t ctx = corpus[j];
                    float* v_in = syn0 + (int64_t)ctx * dim;
                    std::fill(neu1e.begin(), neu1e.end(), 0.0f);
                    for (int32_t k = 0; k <= negative; ++k) {
                        int32_t target;
                        float label;
                        if (k == 0) {
                            target = center;
                            label = 1.0f;
                        } else {
                            target = table[next_rand(&rng) %
                                           (uint64_t)table_size];
                            if (target == center) continue;
                            label = 0.0f;
                        }
                        float* v_out = syn1neg + (int64_t)target * dim;
                        float f = 0.0f;
                        for (int64_t d = 0; d < dim; ++d)
                            f += v_in[d] * v_out[d];
                        float p = fast_sigmoid(f);
                        float g = (label - p) * lr;
                        loss_sum += label > 0.5f
                                        ? -std::log(std::max(p, 1e-7f))
                                        : -std::log(std::max(1.0f - p,
                                                             1e-7f));
                        for (int64_t d = 0; d < dim; ++d) {
                            neu1e[(size_t)d] += g * v_out[d];
                            v_out[d] += g * v_in[d];
                        }
                    }
                    for (int64_t d = 0; d < dim; ++d)
                        v_in[d] += neu1e[(size_t)d];
                    ++pair_count;
                }
            }
        }
    }
    return pair_count > 0
               ? loss_sum / (double)(pair_count * (negative + 1))
               : 0.0;
}

int32_t native_abi_version() { return 1; }

}  // extern "C"
