// Native batch tokenizer / corpus encoder.
//
// The reference's text pipeline tokenizes on the JVM
// (text/tokenization/tokenizerfactory/DefaultTokenizerFactory.java with
// CommonPreprocessor — whitespace split, strip [digits .:,"'()[]|/?!;],
// lowercase). Word2Vec/TF-IDF re-tokenize the whole corpus every epoch,
// which makes tokenization a real host-side hot path; this is the C++
// analog, OpenMP-parallel over documents.
//
// Semantics mirror the Python DefaultTokenizerFactory(CommonPreprocessor)
// for ASCII text (lowercasing here is byte-level; callers fall back to
// the Python path for non-ASCII input — text/native_tokenizer.py guards).
//
// Exposed via ctypes (no pybind11 in the image):
//   dl4j_vocab_create / dl4j_vocab_free        word -> id hash
//   dl4j_tokenize_encode                       corpus -> per-doc id arrays
//   dl4j_count_tokens / dl4j_counts_*          corpus -> (word, count) set
#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

inline bool is_space(unsigned char c) {
    // Python str.split() whitespace, ASCII part: space, \t, \r, \f, \v
    // plus the FS/GS/RS/US separators 0x1c-0x1f ('\n' is the doc
    // delimiter, handled by split_lines)
    return c == ' ' || c == '\t' || c == '\r' || c == '\f' || c == '\v' ||
           (c >= 0x1c && c <= 0x1f);
}

inline bool is_stripped(unsigned char c) {
    // CommonPreprocessor regex class: [\d.:,"'()\[\]|/?!;]
    switch (c) {
        case '.': case ':': case ',': case '"': case '\'':
        case '(': case ')': case '[': case ']': case '|':
        case '/': case '?': case '!': case ';':
            return true;
        default:
            return c >= '0' && c <= '9';
    }
}

inline char low(unsigned char c) {
    return (c >= 'A' && c <= 'Z') ? char(c - 'A' + 'a') : char(c);
}

// preprocess one whitespace-delimited raw token into `out`; returns false
// if the token is empty after stripping
bool preprocess(const char* s, int64_t len, bool common, std::string& out) {
    out.clear();
    for (int64_t i = 0; i < len; ++i) {
        unsigned char c = (unsigned char)s[i];
        if (common) {
            if (is_stripped(c)) continue;
            out.push_back(low(c));
        } else {
            out.push_back((char)c);
        }
    }
    return !out.empty();
}

// tokenize one line into preprocessed tokens
template <typename F>
void for_tokens(const char* s, int64_t len, bool common, F&& f) {
    std::string buf;
    int64_t i = 0;
    while (i < len) {
        while (i < len && is_space((unsigned char)s[i])) ++i;
        int64_t start = i;
        while (i < len && !is_space((unsigned char)s[i])) ++i;
        if (i > start && preprocess(s + start, i - start, common, buf))
            f(buf);
    }
}

struct Vocab {
    std::unordered_map<std::string, int32_t> map;
};

struct Counts {
    std::unordered_map<std::string, int64_t> map;
    // export staging (filled by dl4j_counts_export_prepare)
    std::string blob;
    std::vector<int64_t> offsets;   // n+1 entries into blob
    std::vector<int64_t> counts;
};

std::vector<std::pair<int64_t, int64_t>> split_lines(const char* text,
                                                     int64_t len) {
    std::vector<std::pair<int64_t, int64_t>> lines;
    int64_t start = 0;
    for (int64_t i = 0; i < len; ++i) {
        if (text[i] == '\n') {
            lines.emplace_back(start, i);
            start = i + 1;
        }
    }
    if (start < len) lines.emplace_back(start, len);
    return lines;
}

}  // namespace

extern "C" {

void* dl4j_vocab_create(const char* blob, const int64_t* offsets,
                        int64_t n_words) {
    auto* v = new Vocab();
    v->map.reserve((size_t)n_words * 2);
    for (int64_t i = 0; i < n_words; ++i) {
        v->map.emplace(std::string(blob + offsets[i],
                                   (size_t)(offsets[i + 1] - offsets[i])),
                       (int32_t)i);
    }
    return v;
}

void dl4j_vocab_free(void* h) { delete (Vocab*)h; }

// Encode a '\n'-separated corpus. Writes token ids to out_ids (OOV tokens
// are skipped unless keep_oov, then written as -1), per-doc END offsets
// into doc_ends. Returns total ids written, or -(needed) if max_out was
// too small (call again with a bigger buffer), or INT64_MIN when
// max_docs is too small (distinct from the resize protocol — a resize
// loop must not spin on it).
int64_t dl4j_tokenize_encode(void* vocab_h, const char* text, int64_t len,
                             int common, int keep_oov,
                             int32_t* out_ids, int64_t max_out,
                             int64_t* doc_ends, int64_t max_docs,
                             int64_t* n_docs_out) {
    auto* vocab = (Vocab*)vocab_h;
    auto lines = split_lines(text, len);
    int64_t n_docs = (int64_t)lines.size();
    if (n_docs > max_docs) return INT64_MIN;
    std::vector<std::vector<int32_t>> per_doc((size_t)n_docs);

#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic, 16)
#endif
    for (int64_t d = 0; d < n_docs; ++d) {
        auto& ids = per_doc[(size_t)d];
        for_tokens(text + lines[(size_t)d].first,
                   lines[(size_t)d].second - lines[(size_t)d].first,
                   common != 0, [&](const std::string& tok) {
                       auto it = vocab->map.find(tok);
                       if (it != vocab->map.end())
                           ids.push_back(it->second);
                       else if (keep_oov)
                           ids.push_back(-1);
                   });
    }

    int64_t total = 0;
    for (auto& ids : per_doc) total += (int64_t)ids.size();
    if (total > max_out) return -total;
    int64_t pos = 0;
    for (int64_t d = 0; d < n_docs; ++d) {
        auto& ids = per_doc[(size_t)d];
        if (!ids.empty())
            std::memcpy(out_ids + pos, ids.data(),
                        ids.size() * sizeof(int32_t));
        pos += (int64_t)ids.size();
        doc_ends[d] = pos;
    }
    *n_docs_out = n_docs;
    return total;
}

// Count unique preprocessed tokens across the corpus (vocab building).
void* dl4j_count_tokens(const char* text, int64_t len, int common) {
    auto lines = split_lines(text, len);
    int64_t n_docs = (int64_t)lines.size();
#ifdef _OPENMP
    int n_threads = omp_get_max_threads();
#else
    int n_threads = 1;
#endif
    std::vector<std::unordered_map<std::string, int64_t>> partial(
        (size_t)n_threads);

#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic, 16)
#endif
    for (int64_t d = 0; d < n_docs; ++d) {
#ifdef _OPENMP
        auto& local = partial[(size_t)omp_get_thread_num()];
#else
        auto& local = partial[0];
#endif
        for_tokens(text + lines[(size_t)d].first,
                   lines[(size_t)d].second - lines[(size_t)d].first,
                   common != 0,
                   [&](const std::string& tok) { ++local[tok]; });
    }

    auto* c = new Counts();
    for (auto& p : partial)
        for (auto& kv : p) c->map[kv.first] += kv.second;

    c->offsets.reserve(c->map.size() + 1);
    c->counts.reserve(c->map.size());
    c->offsets.push_back(0);
    for (auto& kv : c->map) {
        c->blob += kv.first;
        c->offsets.push_back((int64_t)c->blob.size());
        c->counts.push_back(kv.second);
    }
    return c;
}

int64_t dl4j_counts_size(void* h) { return (int64_t)((Counts*)h)->counts.size(); }
int64_t dl4j_counts_blob_len(void* h) { return (int64_t)((Counts*)h)->blob.size(); }

void dl4j_counts_export(void* h, char* blob, int64_t* offsets,
                        int64_t* counts) {
    auto* c = (Counts*)h;
    std::memcpy(blob, c->blob.data(), c->blob.size());
    std::memcpy(offsets, c->offsets.data(),
                c->offsets.size() * sizeof(int64_t));
    std::memcpy(counts, c->counts.data(),
                c->counts.size() * sizeof(int64_t));
}

void dl4j_counts_free(void* h) { delete (Counts*)h; }

}  // extern "C"
