// ndarray_ops — the host half of the INDArray op contract.
//
// SURVEY.md §2.1: the reference consumes ND4J's C++ kernel library
// (libnd4j) through the INDArray surface — gemm
// (LSTMHelpers.java:212,522,616), im2col (ConvolutionLayer.java:215),
// elementwise Transforms, reductions, broadcasts, random. On TPU the
// device half of that contract IS XLA (by-design collapse, SURVEY §7);
// this file is the "nd4j-native backend" analog: the host CPU fallback /
// ETL path of the same op surface, OpenMP-parallel, plain C ABI for
// ctypes. Consumers: deeplearning4j_tpu/native/ndarray.py (HostNDArray),
// clustering (pairwise distances), data fetchers (u8→f32 scale).
//
// All matrices are row-major f32; callers flatten leading dims so every
// reduction/broadcast is a (rows, cols) problem.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

inline uint64_t splitmix(uint64_t* s) {
    uint64_t z = (*s += 0x9E3779B97F4A7C15ULL);
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
}

inline float u01(uint64_t* s) {
    return (float)((splitmix(s) >> 40) * (1.0 / 16777216.0));
}

}  // namespace

extern "C" {

// ---------------------------------------------------------- BLAS L1 ----
float dot_f32(const float* x, const float* y, int64_t n) {
    double acc = 0.0;
#ifdef _OPENMP
#pragma omp parallel for reduction(+ : acc) if (n > 65536)
#endif
    for (int64_t i = 0; i < n; ++i) acc += (double)x[i] * y[i];
    return (float)acc;
}

void axpy_f32(float alpha, const float* x, float* y, int64_t n) {
#ifdef _OPENMP
#pragma omp parallel for if (n > 65536)
#endif
    for (int64_t i = 0; i < n; ++i) y[i] += alpha * x[i];
}

float nrm2_f32(const float* x, int64_t n) {
    double acc = 0.0;
#ifdef _OPENMP
#pragma omp parallel for reduction(+ : acc) if (n > 65536)
#endif
    for (int64_t i = 0; i < n; ++i) acc += (double)x[i] * x[i];
    return (float)std::sqrt(acc);
}

// ---------------------------------------------------------- BLAS L3 ----
// C = alpha * op(A) @ op(B) + beta * C, row-major. Blocked + OpenMP over
// row panels; the inner kernel is the k-outer ikj order so the compiler
// vectorizes the j loop (no transposed loads in the hot path: op(A)/op(B)
// are materialized panel-wise).
void gemm_f32(int32_t trans_a, int32_t trans_b, int64_t m, int64_t n,
              int64_t k, float alpha, const float* A, const float* B,
              float beta, float* C) {
    const int64_t MC = 64, KC = 256;
#ifdef _OPENMP
#pragma omp parallel if (m * n * k > 1 << 18)
#endif
    {
        float* a_panel = new float[MC * KC];
#ifdef _OPENMP
#pragma omp for schedule(static)
#endif
        for (int64_t i0 = 0; i0 < m; i0 += MC) {
            int64_t ib = std::min(MC, m - i0);
            for (int64_t i = i0; i < i0 + ib; ++i)
                for (int64_t j = 0; j < n; ++j)
                    C[i * n + j] = beta == 0.0f ? 0.0f : C[i * n + j] * beta;
            for (int64_t k0 = 0; k0 < k; k0 += KC) {
                int64_t kb = std::min(KC, k - k0);
                // pack op(A)[i0:i0+ib, k0:k0+kb]
                for (int64_t i = 0; i < ib; ++i)
                    for (int64_t kk = 0; kk < kb; ++kk)
                        a_panel[i * kb + kk] =
                            trans_a ? A[(k0 + kk) * m + (i0 + i)]
                                    : A[(i0 + i) * k + (k0 + kk)];
                for (int64_t i = 0; i < ib; ++i) {
                    float* c_row = C + (i0 + i) * n;
                    for (int64_t kk = 0; kk < kb; ++kk) {
                        float a = alpha * a_panel[i * kb + kk];
                        const float* b_row =
                            trans_b ? nullptr : B + (k0 + kk) * n;
                        if (trans_b) {
                            for (int64_t j = 0; j < n; ++j)
                                c_row[j] += a * B[j * k + (k0 + kk)];
                        } else {
                            for (int64_t j = 0; j < n; ++j)
                                c_row[j] += a * b_row[j];
                        }
                    }
                }
            }
        }
        delete[] a_panel;
    }
}

// ------------------------------------------------------- elementwise ----
// Transform op codes (keep in sync with ndarray.py):
// 0 exp 1 log 2 tanh 3 sigmoid 4 relu 5 sqrt 6 abs 7 neg 8 square
// 9 add_scalar 10 mul_scalar 11 pow_scalar 12 clip_min 13 clip_max
// 14 sign 15 reciprocal
void transform_f32(int32_t op, const float* x, int64_t n, float arg,
                   float* out) {
#ifdef _OPENMP
#pragma omp parallel for if (n > 32768)
#endif
    for (int64_t i = 0; i < n; ++i) {
        float v = x[i];
        switch (op) {
            case 0: v = std::exp(v); break;
            case 1: v = std::log(v); break;
            case 2: v = std::tanh(v); break;
            case 3: v = 1.0f / (1.0f + std::exp(-v)); break;
            case 4: v = std::max(v, 0.0f); break;  // NaN propagates, as numpy
            case 5: v = std::sqrt(v); break;
            case 6: v = std::fabs(v); break;
            case 7: v = -v; break;
            case 8: v = v * v; break;
            case 9: v = v + arg; break;
            case 10: v = v * arg; break;
            case 11: v = std::pow(v, arg); break;
            case 12: v = std::max(v, arg); break;
            case 13: v = std::min(v, arg); break;
            case 14: v = (v > 0.0f) - (v < 0.0f); break;
            case 15: v = 1.0f / v; break;
        }
        out[i] = v;
    }
}

// Binary op codes: 0 add 1 sub 2 mul 3 div 4 max 5 min
void binary_f32(int32_t op, const float* a, const float* b, int64_t n,
                float* out) {
#ifdef _OPENMP
#pragma omp parallel for if (n > 32768)
#endif
    for (int64_t i = 0; i < n; ++i) {
        float x = a[i], y = b[i], v = 0.0f;
        switch (op) {
            case 0: v = x + y; break;
            case 1: v = x - y; break;
            case 2: v = x * y; break;
            case 3: v = x / y; break;
            case 4: v = std::max(x, y); break;
            case 5: v = std::min(x, y); break;
        }
        out[i] = v;
    }
}

// Broadcast a length-`cols` vector over each row. Same binary op codes.
void broadcast_row_f32(int32_t op, const float* x, int64_t rows,
                       int64_t cols, const float* vec, float* out) {
#ifdef _OPENMP
#pragma omp parallel for if (rows * cols > 32768)
#endif
    for (int64_t r = 0; r < rows; ++r) {
        const float* xr = x + r * cols;
        float* or_ = out + r * cols;
        for (int64_t c = 0; c < cols; ++c) {
            float a = xr[c], b = vec[c], v = 0.0f;
            switch (op) {
                case 0: v = a + b; break;
                case 1: v = a - b; break;
                case 2: v = a * b; break;
                case 3: v = a / b; break;
                case 4: v = std::max(a, b); break;
                case 5: v = std::min(a, b); break;
            }
            or_[c] = v;
        }
    }
}

// -------------------------------------------------------- reductions ----
// Reduce op codes: 0 sum 1 mean 2 max 3 min 4 argmax 5 norm2
// axis=1 (per row, out[rows]) or axis=0 (per col, out[cols]).
void reduce_f32(int32_t op, const float* x, int64_t rows, int64_t cols,
                int32_t axis, float* out) {
    if (rows == 0 || cols == 0) {   // empty reduced dim: sum→0, else NaN
        int64_t n = (axis == 1) ? rows : cols;
        float fill = (op == 0) ? 0.0f
                               : std::numeric_limits<float>::quiet_NaN();
        for (int64_t i = 0; i < n; ++i) out[i] = fill;
        return;
    }
    if (axis == 1) {
#ifdef _OPENMP
#pragma omp parallel for if (rows * cols > 32768)
#endif
        for (int64_t r = 0; r < rows; ++r) {
            const float* xr = x + r * cols;
            double acc = 0.0;
            float best = xr[0];
            int64_t arg = 0;
            for (int64_t c = 0; c < cols; ++c) {
                float v = xr[c];
                acc += (op == 5) ? (double)v * v : (double)v;
                if ((op == 2 || op == 4) ? v > best : v < best) {
                    best = v;
                    arg = c;
                }
            }
            switch (op) {
                case 0: out[r] = (float)acc; break;
                case 1: out[r] = (float)(acc / (double)cols); break;
                case 2: case 3: out[r] = best; break;
                case 4: out[r] = (float)arg; break;
                case 5: out[r] = (float)std::sqrt(acc); break;
            }
        }
    } else {
        for (int64_t c = 0; c < cols; ++c) {
            double acc = 0.0;
            float best = x[c];
            int64_t arg = 0;
            for (int64_t r = 0; r < rows; ++r) {
                float v = x[r * cols + c];
                acc += (op == 5) ? (double)v * v : (double)v;
                if ((op == 2 || op == 4) ? v > best : v < best) {
                    best = v;
                    arg = r;
                }
            }
            switch (op) {
                case 0: out[c] = (float)acc; break;
                case 1: out[c] = (float)(acc / (double)rows); break;
                case 2: case 3: out[c] = best; break;
                case 4: out[c] = (float)arg; break;
                case 5: out[c] = (float)std::sqrt(acc); break;
            }
        }
    }
}

// ------------------------------------------------------------ im2col ----
// NCHW im2col (ConvolutionLayer.java:215 contract): input [C,H,W] →
// columns [C*kh*kw, oh*ow]. Host-side only (XLA convs never materialize
// this); exists for op-contract parity and CPU fallback testing.
void im2col_f32(const float* img, int64_t C, int64_t H, int64_t W,
                int64_t kh, int64_t kw, int64_t sh, int64_t sw,
                int64_t ph, int64_t pw, float* cols) {
    int64_t oh = (H + 2 * ph - kh) / sh + 1;
    int64_t ow = (W + 2 * pw - kw) / sw + 1;
#ifdef _OPENMP
#pragma omp parallel for collapse(2) if (C * kh * kw > 64)
#endif
    for (int64_t c = 0; c < C; ++c)
        for (int64_t ki = 0; ki < kh; ++ki)
            for (int64_t kj = 0; kj < kw; ++kj) {
                float* dst = cols + ((c * kh + ki) * kw + kj) * oh * ow;
                for (int64_t y = 0; y < oh; ++y) {
                    int64_t iy = y * sh + ki - ph;
                    for (int64_t x = 0; x < ow; ++x) {
                        int64_t ix = x * sw + kj - pw;
                        dst[y * ow + x] =
                            (iy >= 0 && iy < H && ix >= 0 && ix < W)
                                ? img[(c * H + iy) * W + ix]
                                : 0.0f;
                    }
                }
            }
}

void col2im_f32(const float* cols, int64_t C, int64_t H, int64_t W,
                int64_t kh, int64_t kw, int64_t sh, int64_t sw,
                int64_t ph, int64_t pw, float* img) {
    int64_t oh = (H + 2 * ph - kh) / sh + 1;
    int64_t ow = (W + 2 * pw - kw) / sw + 1;
    std::memset(img, 0, sizeof(float) * (size_t)(C * H * W));
    for (int64_t c = 0; c < C; ++c)
        for (int64_t ki = 0; ki < kh; ++ki)
            for (int64_t kj = 0; kj < kw; ++kj) {
                const float* src = cols + ((c * kh + ki) * kw + kj) * oh * ow;
                for (int64_t y = 0; y < oh; ++y) {
                    int64_t iy = y * sh + ki - ph;
                    if (iy < 0 || iy >= H) continue;
                    for (int64_t x = 0; x < ow; ++x) {
                        int64_t ix = x * sw + kj - pw;
                        if (ix >= 0 && ix < W)
                            img[(c * H + iy) * W + ix] += src[y * ow + x];
                    }
                }
            }
}

// ------------------------------------------------------------ random ----
void random_uniform_f32(uint64_t seed, int64_t n, float lo, float hi,
                        float* out) {
    uint64_t s = seed ? seed : 1;
    for (int64_t i = 0; i < n; ++i) out[i] = lo + (hi - lo) * u01(&s);
}

void random_gaussian_f32(uint64_t seed, int64_t n, float mean, float std,
                         float* out) {
    uint64_t s = seed ? seed : 1;
    for (int64_t i = 0; i < n; i += 2) {
        float u1 = std::max(u01(&s), 1e-12f), u2 = u01(&s);
        float r = std::sqrt(-2.0f * std::log(u1));
        out[i] = mean + std * r * std::cos(6.28318530718f * u2);
        if (i + 1 < n)
            out[i + 1] = mean + std * r * std::sin(6.28318530718f * u2);
    }
}

// ---------------------------------------------------- distance / ETL ----
// out[i,j] = ||X[i] - Q[j]||² — the host hot loop of VP-tree/KD-tree/
// k-means/KNN-server queries (reference keeps these host-side too,
// SURVEY §7 "host-side algorithms don't belong on TPU").
void pairwise_sqdist_f32(const float* X, int64_t n, const float* Q,
                         int64_t m, int64_t d, float* out) {
#ifdef _OPENMP
#pragma omp parallel for if (n * m * d > 1 << 16)
#endif
    for (int64_t i = 0; i < n; ++i) {
        const float* xi = X + i * d;
        for (int64_t j = 0; j < m; ++j) {
            const float* qj = Q + j * d;
            double acc = 0.0;
            for (int64_t k = 0; k < d; ++k) {
                float diff = xi[k] - qj[k];
                acc += (double)diff * diff;
            }
            out[i * m + j] = (float)acc;
        }
    }
}

// u8 → f32 scale+shift: the byte-image ETL inner loop of the dataset
// fetchers (MnistDataFetcher-style normalization) without a Python pass.
void scale_u8_f32(const uint8_t* src, int64_t n, float scale, float shift,
                  float* out) {
#ifdef _OPENMP
#pragma omp parallel for if (n > 1 << 16)
#endif
    for (int64_t i = 0; i < n; ++i) out[i] = (float)src[i] * scale + shift;
}

}  // extern "C"
