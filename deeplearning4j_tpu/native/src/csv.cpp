// Fast numeric CSV parsing for the DataVec record-reader bridge.
//
// Parity role: the reference's data loading leans on native code (DataVec
// readers over libnd4j buffers); here the hot path of
// data/records.py:CSVRecordReader — all-numeric CSV -> float32 matrix —
// is one strict C++ pass. STRICT means: every field must parse fully as
// a number and every row must have the same arity; anything else returns
// an error code and the caller falls back to the Python csv module
// (which handles quoting, mixed types, etc.). No silent zeros.

#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>

namespace {
// strtof is LC_NUMERIC-dependent; parse under an explicit "C" locale so
// accept/reject behavior matches python float() regardless of process
// locale settings
locale_t c_locale() {
    static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
    return loc;
}
}   // namespace

extern "C" {

// Pass 1 (out == nullptr): validate + count; writes column count to
// *n_cols_io and returns the row count.
// Pass 2 (out != nullptr): fill out[rows * cols] row-major.
// Returns >= 0 rows on success; -1 non-numeric field; -2 ragged row;
// -3 overflow of max_rows (pass 2 only).
int64_t csv_parse_f32(const char* buf, int64_t n, char delim,
                      int64_t skip_lines, float* out, int64_t max_rows,
                      int64_t* n_cols_io) {
    int64_t pos = 0;
    for (int64_t s = 0; s < skip_lines && pos < n; ++s) {
        while (pos < n && buf[pos] != '\n') ++pos;
        if (pos < n) ++pos;
    }
    int64_t rows = 0;
    int64_t cols = (out != nullptr && n_cols_io) ? *n_cols_io : -1;
    if (out == nullptr) {
        // counting pass: newline scan + first-line arity only; full
        // numeric + arity validation happens in the fill pass
        int64_t p = pos;
        while (p < n) {
            if (buf[p] == '\n' || buf[p] == '\r') {
                ++p;
                continue;
            }
            const char* nl = (const char*)std::memchr(buf + p, '\n', n - p);
            int64_t line_end = nl ? (nl - buf) : n;
            if (cols < 0) {
                cols = 1;
                for (int64_t i = p; i < line_end; ++i)
                    if (buf[i] == delim) ++cols;
            }
            ++rows;
            p = line_end + 1;
        }
        if (n_cols_io) *n_cols_io = cols < 0 ? 0 : cols;
        return rows;
    }
    while (pos < n) {
        // skip blank lines (incl. a trailing newline at EOF)
        if (buf[pos] == '\n' || buf[pos] == '\r') {
            ++pos;
            continue;
        }
        int64_t line_end = pos;
        while (line_end < n && buf[line_end] != '\n') ++line_end;
        int64_t end = line_end;
        if (end > pos && buf[end - 1] == '\r') --end;

        // in-place strtof: the caller's buffer is NUL-terminated (CPython
        // bytes) and strtof stops at the delimiter/newline on its own
        int64_t c = 0;
        int64_t field_start = pos;
        for (int64_t i = pos; i <= end; ++i) {
            if (i == end || buf[i] == delim) {
                const char* fs = buf + field_start;
                const char* fe = buf + i;
                while (fs < fe && std::isspace((unsigned char)*fs)) ++fs;
                if (fs == fe) return -1;        // empty field: not numeric
                // strtof accepts hex floats ("0x10") and nan payloads
                // ("nan(abc)") that python float() rejects — refuse both
                // so the parsers agree
                for (const char* q = fs; q < fe; ++q)
                    if (*q == 'x' || *q == 'X' || *q == '(') return -1;
                char* parse_end = nullptr;
                float v = strtof_l(fs, &parse_end, c_locale());
                if (parse_end == fs) return -1;
                while (parse_end < fe &&
                       std::isspace((unsigned char)*parse_end))
                    ++parse_end;
                if (parse_end != fe) return -1; // partial parse
                if (!std::isfinite(v)) {
                    // only accept non-finite when the text says so
                    // (python float() parses "inf"/"nan" too); a finite
                    // literal overflowing f32 (1e39) must fall back
                    const char* t = fs;
                    if (*t == '+' || *t == '-') ++t;
                    char c0 = (char)std::tolower((unsigned char)*t);
                    if (c0 != 'i' && c0 != 'n') return -1;
                }
                if (out != nullptr) {
                    if (rows >= max_rows) return -3;
                    if (c >= cols) return -2;
                    out[rows * cols + c] = v;
                }
                ++c;
                field_start = i + 1;
            }
        }
        if (cols < 0)
            cols = c;
        else if (c != cols)
            return -2;                           // ragged row
        ++rows;
        pos = line_end + 1;
    }
    if (n_cols_io) *n_cols_io = cols < 0 ? 0 : cols;
    return rows;
}

}   // extern "C"
