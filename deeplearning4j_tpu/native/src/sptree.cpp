// Barnes-Hut sp-tree repulsion for t-SNE (host-side, O(N log N)).
//
// Parity target: the reference's
// deeplearning4j-nearestneighbors-parent/nearestneighbor-core/src/main/
// java/org/deeplearning4j/clustering/sptree/SpTree.java (generic-dim
// space-partitioning tree with center-of-mass subdivision) and
// deeplearning4j-manifold/deeplearning4j-tsne/.../BarnesHutTsne.java
// (computeNonEdgeForces with the theta criterion). Re-implemented from
// the algorithm, not the code: flat arena allocation instead of node
// objects, iterative traversal with an explicit stack, OpenMP over
// points.
//
// Supports dim in {2, 3} (t-SNE embedding dims); 2^dim children.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

namespace {

struct Arena {
    // Node i: center[dim], half-width hw (uniform cube), center of mass
    // com[dim], cumulative count, child index base (-1 = leaf), point
    // index (-1 = empty/internal).
    std::vector<float> center, com;
    std::vector<float> hw;
    std::vector<int64_t> count, child_base, point;
    int dim;
    int fanout;

    explicit Arena(int d) : dim(d), fanout(1 << d) {}

    int64_t alloc(const float* c, float h) {
        int64_t id = (int64_t)hw.size();
        for (int k = 0; k < dim; ++k) center.push_back(c[k]);
        for (int k = 0; k < dim; ++k) com.push_back(0.0f);
        hw.push_back(h);
        count.push_back(0);
        child_base.push_back(-1);
        point.push_back(-1);
        return id;
    }

    int child_slot(int64_t node, const float* y) const {
        int slot = 0;
        for (int k = 0; k < dim; ++k)
            if (y[k] > center[node * dim + k]) slot |= (1 << k);
        return slot;
    }

    void subdivide(int64_t node) {
        float h = hw[node] * 0.5f;
        int64_t base = (int64_t)hw.size();
        for (int s = 0; s < fanout; ++s) {
            float c[3];
            for (int k = 0; k < dim; ++k)
                c[k] = center[node * dim + k] + ((s >> k) & 1 ? h : -h);
            alloc(c, h);
        }
        child_base[node] = base;
    }

    void insert(int64_t node, const float* y, int64_t pidx) {
        for (;;) {
            // update cumulative center of mass on the way down
            double cnt = (double)count[node];
            for (int k = 0; k < dim; ++k)
                com[node * dim + k] = (float)(
                    (com[node * dim + k] * cnt + y[k]) / (cnt + 1.0));
            count[node] += 1;
            if (child_base[node] < 0 && point[node] < 0) {   // empty leaf
                point[node] = pidx;
                return;
            }
            if (hw[node] < 1e-9f)   // depth cap: merge into count/com
                return;
            if (child_base[node] < 0) {         // occupied leaf: split
                int64_t old = point[node];
                const float* oy = y_all + old * dim;
                // duplicate-point guard: nudge into count only
                bool same = true;
                for (int k = 0; k < dim; ++k)
                    if (oy[k] != y[k]) { same = false; break; }
                if (same) return;   // keep as multiplicity in count/com
                subdivide(node);
                int64_t tgt = child_base[node] + child_slot(node, oy);
                // push the old occupant one level down, PRESERVING its
                // merged-duplicate multiplicity: count[node] was already
                // incremented for the incoming point, so the occupant
                // (plus any exact duplicates merged into this leaf)
                // accounts for count[node] - 1; its com is exactly oy
                // since merged points are bitwise-equal
                for (int k = 0; k < dim; ++k)
                    com[tgt * dim + k] = oy[k];
                count[tgt] = count[node] - 1;
                point[tgt] = old;
                point[node] = -1;
            }
            node = child_base[node] + child_slot(node, y);
        }
    }

    const float* y_all = nullptr;
};

}   // namespace

extern "C" {

// Build the tree over Y (n x dim), then for every point i accumulate the
// Barnes-Hut-approximated repulsive numerator
//     neg_f[i] += q^2 * (y_i - com_cell) * count_cell
// and the partition function Z = sum q * count (q = 1/(1+d^2)), visiting
// a cell as a summary when hw_cell / dist < theta (SpTree.java theta
// condition). Returns Z; stats[0] receives total cells visited (the
// O(N log N) diagnostic).
double bh_repulsion_f32(const float* Y, int64_t n, int32_t dim,
                        float theta, float* neg_f, int64_t* stats) {
    if (n == 0 || dim < 1 || dim > 3) return 0.0;
    // bounding cube
    float lo[3] = {1e30f, 1e30f, 1e30f}, hi[3] = {-1e30f, -1e30f, -1e30f};
    for (int64_t i = 0; i < n; ++i)
        for (int k = 0; k < dim; ++k) {
            lo[k] = std::min(lo[k], Y[i * dim + k]);
            hi[k] = std::max(hi[k], Y[i * dim + k]);
        }
    float c[3] = {0, 0, 0}, h = 0.0f;
    for (int k = 0; k < dim; ++k) {
        c[k] = 0.5f * (lo[k] + hi[k]);
        h = std::max(h, 0.5f * (hi[k] - lo[k]));
    }
    h = std::max(h, 1e-5f) * 1.0001f;   // keep formula in sync: PySpTree

    Arena tree(dim);
    tree.y_all = Y;
    tree.center.reserve((size_t)n * 2 * dim);
    tree.alloc(c, h);
    for (int64_t i = 0; i < n; ++i) tree.insert(0, Y + i * dim, i);

    const float theta2 = theta * theta;
    double z_total = 0.0;
    int64_t visits_total = 0;

#ifdef _OPENMP
#pragma omp parallel for reduction(+ : z_total, visits_total) \
    schedule(static) if (n > 256)
#endif
    for (int64_t i = 0; i < n; ++i) {
        const float* yi = Y + i * dim;
        float acc[3] = {0, 0, 0};
        double zi = 0.0;
        int64_t visits = 0;
        std::vector<int64_t> stack;
        stack.reserve(256);
        stack.push_back(0);
        while (!stack.empty()) {
            int64_t node = stack.back();
            stack.pop_back();
            ++visits;
            int64_t cnt = tree.count[node];
            if (cnt == 0) continue;
            float d2 = 0.0f, diff[3];
            const float* com = tree.com.data() + node * dim;
            for (int k = 0; k < dim; ++k) {
                diff[k] = yi[k] - com[k];
                d2 += diff[k] * diff[k];
            }
            bool is_self_leaf =
                tree.child_base[node] < 0 && tree.point[node] == i;
            float w = 2.0f * tree.hw[node];   // cell width
            if (tree.child_base[node] < 0 || w * w < theta2 * d2) {
                // leaf or far-enough cell: use the summary
                if (is_self_leaf && cnt == 1) continue;
                double mult = (double)cnt - (is_self_leaf ? 1.0 : 0.0);
                float q = 1.0f / (1.0f + d2);
                zi += mult * q;
                float q2 = q * q;
                for (int k = 0; k < dim; ++k)
                    acc[k] += (float)mult * q2 * diff[k];
            } else {
                for (int s = 0; s < tree.fanout; ++s) {
                    int64_t ch = tree.child_base[node] + s;
                    if (tree.count[ch] > 0) stack.push_back(ch);
                }
            }
        }
        for (int k = 0; k < dim; ++k) neg_f[i * dim + k] = acc[k];
        z_total += zi;
        visits_total += visits;
    }
    if (stats) stats[0] = visits_total;
    return z_total;
}

}   // extern "C"
