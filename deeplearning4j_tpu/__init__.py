"""deeplearning4j_tpu — a TPU-native deep-learning framework.

A ground-up re-design of the capability set of Eclipse Deeplearning4j
(reference: /root/reference, DL4J 1.0.0-SNAPSHOT) for TPU hardware:

- declarative, JSON-serializable network configuration
  (DL4J: deeplearning4j-nn/.../nn/conf/NeuralNetConfiguration.java:584)
- sequential and DAG network containers with fit/evaluate/serialize
  (DL4J: MultiLayerNetwork.java, ComputationGraph.java)
- accelerated-kernel seam (DL4J: cuDNN helpers -> here XLA/Pallas registry)
- data-parallel training over a TPU mesh (DL4J: ParallelWrapper + Spark
  masters -> here pjit/shard_map with ICI collectives)
- evaluation, early stopping, transfer learning, checkpointing, listeners,
  model zoo, word embeddings, nearest neighbors, t-SNE.

The compute path is JAX/XLA (jit-compiled, functional); the design is
TPU-first (static shapes, NHWC layouts, bf16-friendly, MXU-sized matmuls),
not a translation of the reference's class hierarchy.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph import ComputationGraph

__all__ = ["MultiLayerNetwork", "ComputationGraph", "__version__"]
