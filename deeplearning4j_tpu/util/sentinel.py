"""Pytest deadlock sentinel — a wedged test dies WITH diagnostics.

Before this plugin, a deadlocked test was a mute hang: the tier-1
``timeout`` wrapper eventually killed the whole run and CI showed
nothing but the kill. The sentinel watches per-test wall time from a
daemon thread; past the budget it writes util/locks.dump_diagnostics()
— every thread's stack plus the DiagnosedLock holder table, so the
failure reads as "thread A holds X and wants Y; thread B holds Y and
wants X" — then hard-exits 3.

Loaded two ways:

- tests/conftest.py imports the hook (tier-1 gets it automatically);
- ``pytest -p deeplearning4j_tpu.util.sentinel`` loads it standalone
  (how the deliberate-deadlock regression test drives it).

Knobs (util/env.py contract — only the literal ``"0"`` disables):

- ``DL4J_TPU_DEADLOCK_SENTINEL``: kill switch for the whole plugin.
- ``DL4J_TPU_SENTINEL_TIMEOUT``: per-test budget in seconds
  (default 300 — comfortably above the slowest legitimate test, far
  below the tier-1 run budget).

Arming the sentinel also arms util/locks recording, so the holder
table is populated when the dump fires.
"""
from __future__ import annotations

import os
import sys
import threading
import time

import pytest

from deeplearning4j_tpu.util import locks as _locks
from deeplearning4j_tpu.util.env import env_flag, env_float

SENTINEL_EXIT_CODE = 3

#: the running test as ONE atomically-replaced (nodeid, t0) tuple —
#: separate keys would let the watchdog pair a new test's id with the
#: previous test's start time and spuriously kill a healthy run
_state = {"cur": None}
_thread = None


def _enabled() -> bool:
    return env_flag("DL4J_TPU_DEADLOCK_SENTINEL", default=True)


def _timeout_s() -> float:
    return float(env_float("DL4J_TPU_SENTINEL_TIMEOUT", 300.0))


def _loop(timeout_s: float):
    try:
        poll = max(0.05, min(5.0, timeout_s / 4))
        while True:
            time.sleep(poll)
            cur = _state["cur"]
            if cur is None:
                continue
            test, t0 = cur
            if time.monotonic() - t0 > timeout_s:
                # the REAL stderr: pytest's capture buffers sys.stderr
                # in memory, which os._exit would discard — the dump is
                # the whole point of dying
                _locks.dump_diagnostics(
                    out=sys.__stderr__ or sys.stderr,
                    reason=f"test {test} exceeded {timeout_s:.0f}s — "
                           "presumed deadlocked "
                           "(DL4J_TPU_SENTINEL_TIMEOUT raises the "
                           "budget, DL4J_TPU_DEADLOCK_SENTINEL=0 "
                           "disables)")
                # hard exit: a deadlocked run cannot unwind itself, and
                # a prompt loud death beats the outer timeout's mute kill
                os._exit(SENTINEL_EXIT_CODE)
    except Exception:                         # noqa: BLE001 — fail loud:
        # a dead watchdog silently un-arms deadlock detection for the
        # rest of the run
        import traceback
        print("deadlock sentinel watchdog crashed:\n"
              + traceback.format_exc(),
              file=sys.__stderr__ or sys.stderr)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_protocol(item, nextitem):
    global _thread
    if _enabled():
        if _thread is None:
            # arm the lock witness along with the sentinel: the holder
            # table is what turns "hung" into "who holds what"
            _locks.enable_recording(True)
            _thread = threading.Thread(
                target=_loop, args=(_timeout_s(),), daemon=True,
                name="deadlock-sentinel")
            _thread.start()
        _state["cur"] = (item.nodeid, time.monotonic())
    yield
    _state["cur"] = None
