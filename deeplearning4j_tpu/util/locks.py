"""DiagnosedLock — the runtime witness for graftlint's static lock graph.

The static side (analysis/concurrency.py) derives a cross-module lock
acquisition-order graph from the AST; cycles in it gate tier-1
(lock-order-inversion). This module is the other half of the contract:

- `DiagnosedLock` is a drop-in ``threading.Lock``/``RLock`` wrapper
  (``with``, ``acquire``/``release``, ``locked``) carrying the lock's
  *static identity* (``deeplearning4j_tpu.serving.registry.
  ModelRegistry._lock``). When recording is on it notes, per
  acquisition, every (held -> acquired) pair observed on the acquiring
  thread plus a live holder table.
- Tests cross-check: every edge the runtime actually witnessed must
  appear in the static graph — if live execution takes a lock order the
  analyzer never derived, the model (or the code) is wrong, and the
  test says which pair.
- The pytest deadlock sentinel (tests/conftest.py) dumps
  `holder_table()` + every thread's stack when a test wedges, so a
  tier-1 deadlock reads as "thread A holds X wants Y; thread B holds Y
  wants X" instead of a mute timeout kill.

Cost model: recording is OFF by default (``DL4J_TPU_LOCK_DIAG`` opt-in,
only ``"1"`` enables — util/env.py contract) and the recording ops are
single dict/set mutations, GIL-atomic in CPython, so no extra lock is
taken around the user's lock — the witness must never reorder or
serialize what it watches. Tests arm it via `enable_recording()`.
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, Optional, Set, TextIO, Tuple

from deeplearning4j_tpu.util.env import env_flag

#: observed acquisition-order pairs (held_name, acquired_name)
_order_edges: Set[Tuple[str, str]] = set()
#: (lock name, instance id) -> (holder thread name, monotonic acquire
#: time). Keyed per INSTANCE: many locks share one static identity
#: (every CircuitBreaker's `_lock`, every Replica's `_inflight_lock`),
#: and one instance's release must not evict a sibling still held by
#: another thread from the sentinel's table
_holders: Dict[Tuple[str, int], Tuple[str, float]] = {}
#: per-thread stack of currently-held DiagnosedLock names
_held = threading.local()

_recording = env_flag("DL4J_TPU_LOCK_DIAG", default=False)


def enable_recording(on: bool = True) -> None:
    """Arm/disarm edge + holder recording (tests; production uses the
    DL4J_TPU_LOCK_DIAG opt-in)."""
    global _recording
    _recording = bool(on)


def recording_enabled() -> bool:
    return _recording


def reset() -> None:
    """Clear recorded edges/holders (test isolation)."""
    _order_edges.clear()
    _holders.clear()


def observed_edges() -> Set[Tuple[str, str]]:
    """Every (held -> acquired) pair witnessed since the last reset()."""
    return set(_order_edges)


def holder_table() -> Dict[str, Tuple[str, float]]:
    """lock name -> (holder thread, seconds held so far), live. When
    several INSTANCES sharing one static identity are held at once,
    later ones display as ``name#2``, ``name#3`` …"""
    now = time.monotonic()
    out: Dict[str, Tuple[str, float]] = {}
    for (name, _inst), (thread, t0) in sorted(list(_holders.items()),
                                              key=lambda kv: kv[1][1]):
        display, n = name, 1
        while display in out:
            n += 1
            display = f"{name}#{n}"
        out[display] = (thread, now - t0)
    return out


class DiagnosedLock:
    """Drop-in Lock/RLock with a static-graph identity.

    ``name`` should be the lock's static identity so the witness
    cross-check can compare runtime edges against the analyzer's graph
    verbatim; ``reentrant=True`` wraps an RLock.
    """

    __slots__ = ("name", "_lock", "_reentrant", "_count")

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = bool(reentrant)
        self._lock = threading.RLock() if reentrant else threading.Lock()
        self._count = 0          # RLock depth (RLock has no locked())

    # ------------------------------------------------------ lock protocol
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            if self._reentrant:
                self._count += 1          # safe: we hold the lock
            if _recording:
                self._note_acquire()
        return ok

    def release(self) -> None:
        if self._reentrant:
            self._count -= 1              # safe: we still hold the lock
        if _recording:
            self._note_release()
        self._lock.release()

    def __enter__(self) -> "DiagnosedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        if self._reentrant:
            # RLock has no locked() before 3.12; the tracked depth is
            # exact for the owner and a best-effort probe for others
            return self._count > 0
        return self._lock.locked()

    def __repr__(self) -> str:                # pragma: no cover - debug
        return f"DiagnosedLock({self.name!r})"

    # --------------------------------------------------------- recording
    def _note_acquire(self) -> None:
        stack = getattr(_held, "stack", None)
        if stack is None:
            stack = _held.stack = []    # entries: (name, instance id)
        for held_name, _inst in stack:
            # same-name pairs are skipped: the static graph has one node
            # per identity, so instance-vs-instance ordering of one
            # attribute would be a self-loop there (a KNOWN limitation —
            # cross-instance AB/BA of a single attr is invisible to both
            # halves)
            if held_name != self.name:
                _order_edges.add((held_name, self.name))
        _holders[(self.name, id(self))] = (
            threading.current_thread().name, time.monotonic())
        stack.append((self.name, id(self)))

    def _note_release(self) -> None:
        key = (self.name, id(self))
        stack = getattr(_held, "stack", None)
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == key:
                    del stack[i]
                    break
        if not stack or key not in stack:
            # this thread no longer holds THIS instance (re-entrant
            # depth exhausted); sibling instances keep their own entries
            _holders.pop(key, None)


# --------------------------------------------------------- sentinel dump
def dump_diagnostics(out: Optional[TextIO] = None,
                     reason: str = "deadlock suspected") -> None:
    """The deadlock-sentinel payload: the lock-holder table plus every
    live thread's current stack (names included — the PR-13 naming
    policy is what makes this readable). Written to `out` (default
    stderr) in one pass so an ensuing hard exit cannot truncate the
    interesting half."""
    out = out if out is not None else sys.stderr
    lines = [f"==== graftlint deadlock sentinel: {reason} ====",
             "---- lock holder table ----"]
    table = holder_table()
    if table:
        for name in sorted(table):
            thread, held_for = table[name]
            lines.append(f"  {name}  held by {thread!r} "
                         f"for {held_for:.1f}s")
    else:
        lines.append("  (no DiagnosedLock held, or recording is off)")
    lines.append("---- all thread stacks ----")
    frames = sys._current_frames()
    for t in threading.enumerate():
        lines.append(f"-- thread {t.name!r} "
                     f"(daemon={t.daemon}, ident={t.ident}) --")
        frame = frames.get(t.ident)
        if frame is None:
            lines.append("   <no frame>")
            continue
        lines.extend(
            "   " + ln.rstrip("\n")
            for entry in traceback.format_stack(frame)
            for ln in entry.splitlines())
    lines.append("==== end sentinel dump ====")
    out.write("\n".join(lines) + "\n")
    out.flush()
