"""Model serialization — zip checkpoint format.

Parity target: DL4J util/ModelSerializer.java:39-125 — a zip archive with
`configuration.json` (declarative config), `coefficients.bin` (parameters),
`updaterState.bin` (optimizer state). Here:

- configuration.json  : MultiLayerConfiguration / ComputationGraphConfiguration JSON
- coefficients.npz    : params pytree as npz (keys = canonical '/'-joined paths)
- state.npz           : layer state (BN running stats)
- updaterState.bin    : optax optimizer state (flax msgpack)
- metadata.json       : model type, iteration/epoch counters, format version

Restore: `restore_multilayer_network` / `restore_computation_graph` /
`load_model` (auto-detect) — the analogs of ModelSerializer.restore*.
"""
from __future__ import annotations

import io
import json
import os
import zipfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

# owned_leaf: the donated-buffer-safety copy (single-sourced in params)
from deeplearning4j_tpu.util.params import iter_leaves, owned_leaf as _owned

_FORMAT_VERSION = 1


def _tree_to_npz_bytes(tree) -> bytes:
    arrays = {}
    for path, leaf in iter_leaves(tree):
        arrays["/".join(path)] = np.asarray(leaf)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()




def _npz_bytes_to_tree(data: bytes) -> dict:
    buf = io.BytesIO(data)
    loaded = np.load(buf)
    tree: dict = {}
    for key in loaded.files:
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = _owned(loaded[key])
    return tree


def _restore_like(template, loaded):
    """Rebuild `loaded` (dict-of-dicts from npz) into the pytree structure of
    `template` — npz paths lose list-ness (VAE encoder stacks) and drop empty
    dicts (parameter-free layers)."""
    if isinstance(template, dict):
        out = {}
        for k, v in template.items():
            if isinstance(loaded, dict) and k in loaded:
                out[k] = _restore_like(v, loaded[k])
            else:
                out[k] = v       # empty subtree dropped by npz: keep template
        return out
    if isinstance(template, (list, tuple)):
        return [_restore_like(t, loaded[str(i)]) for i, t in enumerate(template)]
    return loaded if loaded is not None else template


def save_model(model, path: str, save_updater: bool = True,
               atomic: bool = True, extra_entries: Optional[dict] = None):
    """Write a model checkpoint zip (ModelSerializer.writeModel).

    `atomic` (default): the zip is written to a same-directory temp file
    and `os.replace`d into place, so a kill mid-save can never leave a
    truncated checkpoint at `path` — readers see either the old complete
    file or the new complete file. `extra_entries` ({name: str|bytes})
    adds caller entries to the archive (the resilience layer stores its
    RNG key / normalizer stats this way)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise ValueError(f"Cannot serialize {type(model)}")

    meta = {
        "format_version": _FORMAT_VERSION,
        "model_type": model_type,
        "iteration_count": model.iteration_count,
        "epoch_count": model.epoch_count,
    }
    # atomic mode needs a real filesystem path (file-like targets — the
    # estimator pickle path writes into a BytesIO — stream directly)
    atomic = atomic and isinstance(path, (str, os.PathLike))
    target = f"{path}.tmp.{os.getpid()}" if atomic else path
    try:
        with zipfile.ZipFile(target, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("configuration.json", model.conf.to_json())
            zf.writestr("coefficients.npz", _tree_to_npz_bytes(model.params))
            zf.writestr("state.npz", _tree_to_npz_bytes(model.state))
            zf.writestr("metadata.json", json.dumps(meta))
            if save_updater and model.opt_state is not None:
                from flax import serialization
                zf.writestr("updaterState.bin",
                            serialization.to_bytes(model.opt_state))
            for name, payload in (extra_entries or {}).items():
                zf.writestr(name, payload)
        if atomic:
            os.replace(target, path)
    except BaseException:
        if atomic:
            try:
                os.remove(target)
            except OSError:
                pass
        raise
    return path


def _restore(path: str, expect_type=None, load_updater: bool = True):
    from deeplearning4j_tpu.nn.conf.network import (
        ComputationGraphConfiguration, MultiLayerConfiguration,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path, "r") as zf:
        meta = json.loads(zf.read("metadata.json"))
        conf_json = zf.read("configuration.json").decode()
        model_type = meta["model_type"]
        if expect_type and model_type != expect_type:
            raise ValueError(f"Checkpoint holds a {model_type}, expected {expect_type}")
        if model_type == "MultiLayerNetwork":
            conf = MultiLayerConfiguration.from_json(conf_json)
            model = MultiLayerNetwork(conf)
        else:
            conf = ComputationGraphConfiguration.from_json(conf_json)
            model = ComputationGraph(conf)
        model.init()
        model.params = _restore_like(model.params,
                                     _npz_bytes_to_tree(zf.read("coefficients.npz")))
        model.state = _restore_like(model.state,
                                    _npz_bytes_to_tree(zf.read("state.npz")))
        model.iteration_count = meta.get("iteration_count", 0)
        model.epoch_count = meta.get("epoch_count", 0)
        model._build_optimizer()
        if load_updater and "updaterState.bin" in zf.namelist():
            from flax import serialization
            # from_bytes yields numpy leaves — take owned copies so the
            # first donated train step can't free numpy-owned memory
            model.opt_state = jax.tree_util.tree_map(
                _owned, serialization.from_bytes(
                    model.opt_state, zf.read("updaterState.bin")))
    return model


def restore_multilayer_network(path: str, load_updater: bool = True):
    return _restore(path, "MultiLayerNetwork", load_updater)


def restore_computation_graph(path: str, load_updater: bool = True):
    return _restore(path, "ComputationGraph", load_updater)


def load_model(path: str, load_updater: bool = True):
    return _restore(path, None, load_updater)
