"""Central typed accessors for every ``DL4J_TPU_*`` environment knob.

One module owns the parsing contract that PRs 5, 7, and 8 each had to
re-fix by hand at scattered ``os.environ`` call sites:

- **Kill switches** (features on by default): ONLY the literal ``"0"``
  disables. Unset, ``""``, ``"false"``, ``"2"`` — anything else — leaves
  the feature ON. A hand-rolled ``== "1"`` silently turns
  ``DL4J_TPU_HOST_CAST=true`` into a disable; a hand-rolled ``!= '1'``
  turns ``""`` into one. Both shipped, both were review findings.
- **Opt-ins** (features off by default): the mirror image — ONLY the
  literal ``"1"`` enables.
- **Values**: ``""`` is UNSET, never a value. ``DL4J_TPU_ETL_WORKERS=''``
  must mean "use the default", not ``int('')`` crashing the fit.

``env_flag``/``env_int``/``env_float``/``env_str`` encode those three
rules once; ``scoped`` sets-and-restores a knob around a block (for
tools that pin a child knob). graftlint's ``env-knob-contract`` rule
(analysis/rules/envknobs.py) flags any ``DL4J_TPU_*`` read that bypasses
this module, so the contract cannot regress silently.

The knob catalog itself lives with each subsystem (docs/DATA_PIPELINE.md
for the data plane, docs/SERVING.md for serving, docs/OBSERVABILITY.md
for telemetry).
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

def env_raw(name: str) -> Optional[str]:
    """The raw value with ``""`` normalized to unset (None). Prefer the
    typed accessors; this exists for save/restore plumbing."""
    v = os.environ.get(name)
    if v is None or v == "":
        return None
    return v


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    """String knob; ``""`` means unset and yields `default`."""
    v = env_raw(name)
    return default if v is None else v


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    """Integer knob; unset/``""`` yields `default`. A non-integer value
    raises ValueError naming the variable (fail loud at startup, not
    deep in a fit loop)."""
    v = env_raw(name)
    if v is None:
        return default
    try:
        return int(v)
    except ValueError:
        raise ValueError(
            f"{name}={v!r}: expected an integer") from None


def env_float(name: str, default: Optional[float] = None) -> Optional[float]:
    """Float knob; unset/``""`` yields `default`."""
    v = env_raw(name)
    if v is None:
        return default
    try:
        return float(v)
    except ValueError:
        raise ValueError(
            f"{name}={v!r}: expected a number") from None


def env_flag(name: str, default: bool = True) -> bool:
    """The one boolean-knob contract (module docstring):

    - `default=True`  (kill switch): ONLY ``"0"`` disables.
    - `default=False` (opt-in):      ONLY ``"1"`` enables.

    Everything else — unset, ``""``, typos — keeps the default, so a
    fat-fingered value can never silently flip a production feature."""
    v = env_raw(name)
    if v is None:
        return default
    if default:
        return v != "0"
    return v == "1"


@contextlib.contextmanager
def scoped(name: str, value: Optional[str]) -> Iterator[None]:
    """Set (or, with ``value=None``, unset) a knob for the extent of the
    block, restoring the previous state on exit — the save/set/restore
    dance tools do around subprocesses, without touching os.environ by
    hand at the call site."""
    prev = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = str(value)
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev
