"""Backend/platform detection helpers.

One predicate for "are we on a real TPU", shared by every fused-kernel
eligibility check. The subtlety: through a PJRT plugin tunnel the
platform name is the PLUGIN's (e.g. "axon"), not "tpu" — a bare
`jax.default_backend() == "tpu"` silently disables the Pallas kernels
on exactly the hardware they exist for. The device_kind still names the
chip ("TPU v5 lite"), so fall back to that.
"""
from __future__ import annotations

import jax


def is_tpu_backend() -> bool:
    """True when the default JAX backend is a real TPU, including
    tunneled PJRT plugins whose platform name differs but whose
    device_kind names the TPU generation."""
    try:
        if jax.default_backend() == "tpu":
            return True
        d = jax.devices()[0]
        if d.platform == "cpu":
            return False
        return "tpu" in (getattr(d, "device_kind", "") or "").lower()
    except Exception:
        return False
