"""Deterministic fault-injection harness for resilience testing.

The production story (docs/FAULT_TOLERANCE.md) is only credible if the
recovery paths are exercised: this module injects the three failure
families a preemptible TPU fleet actually produces —

- **divergence**: a NaN/Inf loss at a chosen step (optimizer state or
  data corruption, bf16 overflow);
- **transient errors**: a retryable exception raised at a chosen step
  (DCN hiccup, preempted host, flaky storage);
- **hard faults**: a simulated crash (kill -9 analog, raised as a
  ``BaseException`` so no recovery layer can swallow it), a simulated
  preemption notice (SIGTERM analog), and dropped transport messages.

Everything is deterministic — schedules are explicit step sets (or an
``every=n`` cadence), so tests and the chaos tool reproduce bit-for-bit.
``FaultInjector.from_env()`` reads ``DL4J_TPU_FAULTS`` so any entry point
(CLI, chaos tool, CI) can be run under faults without code changes:

    DL4J_TPU_FAULTS="nan_at=3,4;transient_every=5;crash_at=11"

`ResilientTrainer` (train/resilience.py) consults the injector at its
step boundaries; `attach_transport_faults` wires the message-drop
schedule into a `SocketTransport`.
"""
from __future__ import annotations

import logging
import os
from typing import Iterable, Optional, Set, Tuple

from deeplearning4j_tpu.util.env import env_str

log = logging.getLogger("deeplearning4j_tpu")


class TransientFaultError(RuntimeError):
    """A retryable failure (network hiccup / preempted peer / flaky IO).
    The resilience layer's backoff-and-retry policy treats this class —
    and real ConnectionError/TimeoutError/OSError — as transient."""


class SimulatedCrash(BaseException):
    """A hard kill (SIGKILL / machine loss analog). Derives from
    BaseException ON PURPOSE: no except-Exception recovery path may
    swallow it — exactly like a real kill, the process dies with
    whatever checkpoints already landed on disk."""


def _parse_steps(spec: str) -> Set[int]:
    return {int(tok) for tok in spec.split(",") if tok.strip() != ""}


class FaultInjector:
    """Deterministic, schedule-driven fault source.

    Step indices refer to the trainer's global *dispatch* counter (batches
    consumed across the whole fit, starting at 0). Each scheduled fault
    fires exactly once per step index — a retry of the same step does not
    re-fire the fault, which is what makes transient-retry testable.

    Parameters
    ----------
    nan_at:           steps whose loss is replaced with NaN (divergence).
    transient_at:     steps that raise TransientFaultError before dispatch.
    transient_every:  additionally raise every n-th step (n > 0).
    crash_at:         step that raises SimulatedCrash (uncatchable by the
                      retry layer; the test harness catches it).
    preempt_at:       step at which `should_preempt` turns True (SIGTERM
                      analog delivered through the trainer's flag).
    drop_send_at:     0-based outbound message ordinals a wrapped
                      SocketTransport silently drops.
    etl_stall_at:     steps whose batch fetch is delayed by
                      ``etl_stall_s`` (a throttled input pipeline — the
                      goodput ledger must bill it to data_wait and the
                      step-time anomaly detector must trip on it).
    etl_stall_s:      the injected fetch delay in seconds.
    """

    def __init__(self, nan_at: Iterable[int] = (),
                 transient_at: Iterable[int] = (),
                 transient_every: Optional[int] = None,
                 crash_at: Optional[int] = None,
                 preempt_at: Optional[int] = None,
                 drop_send_at: Iterable[int] = (),
                 etl_stall_at: Iterable[int] = (),
                 etl_stall_s: float = 0.0):
        self.nan_at = set(nan_at)
        self.transient_at = set(transient_at)
        self.transient_every = transient_every
        self.crash_at = crash_at
        self.preempt_at = preempt_at
        self.drop_send_at = set(drop_send_at)
        self.etl_stall_at = set(etl_stall_at)
        self.etl_stall_s = float(etl_stall_s)
        self._fired: Set[Tuple[str, int]] = set()
        self.nans_injected = 0
        self.transients_injected = 0
        self.sends_dropped = 0
        self.stalls_injected = 0

    # ------------------------------------------------------------- parsing
    @classmethod
    def from_env(cls, var: str = "DL4J_TPU_FAULTS") -> Optional["FaultInjector"]:
        """Build an injector from ``nan_at=..;transient_every=..`` env
        syntax; None when the variable is unset/empty."""
        spec = env_str(var, "").strip()
        if not spec:
            return None
        kw: dict = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            key, _, val = part.partition("=")
            key = key.strip()
            if key in ("nan_at", "transient_at", "drop_send_at",
                       "etl_stall_at"):
                kw[key] = _parse_steps(val)
            elif key in ("transient_every", "crash_at", "preempt_at"):
                kw[key] = int(val)
            elif key == "etl_stall_s":
                kw[key] = float(val)
            else:
                raise ValueError(f"{var}: unknown fault key {key!r}")
        log.warning("fault injection ACTIVE from $%s: %s", var, spec)
        return cls(**kw)

    def _once(self, kind: str, step: int) -> bool:
        if (kind, step) in self._fired:
            return False
        self._fired.add((kind, step))
        return True

    # ----------------------------------------------------------- injection
    def before_step(self, step: int):
        """Called before dispatching step `step`; may raise."""
        if self.crash_at is not None and step == self.crash_at \
                and self._once("crash", step):
            log.warning("injecting simulated crash at step %d", step)
            raise SimulatedCrash(f"injected crash at step {step}")
        transient = step in self.transient_at or (
            self.transient_every and step > 0
            and step % self.transient_every == 0)
        if transient and self._once("transient", step):
            self.transients_injected += 1
            log.warning("injecting transient fault at step %d", step)
            raise TransientFaultError(f"injected transient fault at step {step}")

    def before_fetch(self, step: int):
        """Called inside the trainer's ETL window (before pulling step
        `step`'s batch): sleeps ``etl_stall_s`` on scheduled steps, once
        each — a deterministic throttled-input-pipeline analog."""
        if step in self.etl_stall_at and self.etl_stall_s > 0 \
                and self._once("etl_stall", step):
            self.stalls_injected += 1
            log.warning("injecting %.3fs ETL stall at step %d",
                        self.etl_stall_s, step)
            import time
            time.sleep(self.etl_stall_s)

    def corrupt_loss(self, step: int, loss: float) -> float:
        """Replace the loss with NaN on scheduled steps (the observable
        signature of a NaN gradient — the skip-step guard keys off it)."""
        if step in self.nan_at:
            self.nans_injected += 1
            log.warning("injecting NaN loss at step %d", step)
            return float("nan")
        return loss

    def should_preempt(self, step: int) -> bool:
        return self.preempt_at is not None and step >= self.preempt_at

    # ------------------------------------------------------------ transport
    def send_filter(self, peer: int, ordinal: int) -> bool:
        """False = drop this outbound message (ordinal counts every send
        attempt on the transport, across peers, starting at 0)."""
        if ordinal in self.drop_send_at:
            self.sends_dropped += 1
            log.warning("dropping transport message %d to peer %d",
                        ordinal, peer)
            return False
        return True


# --------------------------------------------------------------- serving
class ServingFaults:
    """Deterministic fault points for the serving fleet (serving/fleet.py,
    serving/router.py, tools/serve_chaos.py).

    Unlike the step-scheduled trainer faults above, serving faults are
    *toggles*: a replica is wedged or it is not, a probe path is
    blackholed or it is not. The process-global instance (``serving_
    faults()``) is consulted by the serving hot paths:

    - ``probe_delay_s`` / ``probe_error``: /healthz and /readyz handlers
      sleep (probe deadline blows -> supervisor sees a wedged replica)
      or return 500 (probe blackhole without paying wall-clock).
    - ``predict_delay_s`` / ``predict_error``: the predict path of THIS
      process turns into a straggler (hedging/breaker fodder) or fails
      outright with TransientFaultError (breaker fodder).

    Three ways to engage it, all reaching the same singleton:

    - tests: ``serving_faults().set(predict_delay_s=0.2)`` (and
      ``clear()`` in teardown);
    - env (subprocess replicas wedged from birth):
      ``DL4J_TPU_SERVING_FAULTS="probe_delay_s=5;predict_delay_s=5"``;
    - HTTP (chaos tools wedging a live replica mid-traffic): ``POST
      /v1/faults`` on a ModelServer started with fault injection
      enabled (``--enable-fault-injection``; never on by default).
    """

    _FIELDS = ("probe_delay_s", "predict_delay_s", "probe_error",
               "predict_error")

    def __init__(self):
        self.clear()

    def clear(self):
        self.probe_delay_s = 0.0
        self.predict_delay_s = 0.0
        self.probe_error = False
        self.predict_error = False

    def set(self, **kw) -> "ServingFaults":
        for key, val in kw.items():
            if key not in self._FIELDS:
                raise ValueError(f"unknown serving fault {key!r} "
                                 f"(known: {self._FIELDS})")
            cur = getattr(self, key)
            if isinstance(cur, bool):
                if isinstance(val, str):
                    # env path hands us strings: "0"/"false"/"off" mean
                    # off, not bool("0") == True
                    val = val.strip().lower() not in (
                        "", "0", "false", "no", "off")
                setattr(self, key, bool(val))
            else:
                setattr(self, key, float(val))
        if self.active():
            log.warning("serving fault injection ACTIVE: %s",
                        self.describe())
        return self

    def active(self) -> bool:
        return bool(self.probe_delay_s or self.predict_delay_s
                    or self.probe_error or self.predict_error)

    def describe(self) -> dict:
        return {f: getattr(self, f) for f in self._FIELDS}

    def apply_env(self, var: str = "DL4J_TPU_SERVING_FAULTS"
                  ) -> "ServingFaults":
        """``probe_delay_s=5;predict_error=1`` env syntax; unset/empty
        leaves the toggles untouched."""
        spec = env_str(var, "").strip()
        if not spec:
            return self
        kw = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            key, sep, val = part.partition("=")
            if not sep:
                raise ValueError(f"{var}: expected key=value, got {part!r}")
            kw[key.strip()] = val.strip()
        return self.set(**kw)

    # ------------------------------------------------------ fault points
    def on_probe(self):
        """Consulted by /healthz and /readyz handlers. Sleeps or raises."""
        if self.probe_delay_s > 0:
            import time
            time.sleep(self.probe_delay_s)
        if self.probe_error:
            raise TransientFaultError("injected probe blackhole")

    def on_predict(self):
        """Consulted by the predict path before dispatch."""
        if self.predict_delay_s > 0:
            import time
            time.sleep(self.predict_delay_s)
        if self.predict_error:
            raise TransientFaultError("injected predict fault")


_SERVING_FAULTS = ServingFaults()


def serving_faults() -> ServingFaults:
    """The process-global serving fault toggles (see ServingFaults)."""
    return _SERVING_FAULTS


def attach_transport_faults(transport, injector: FaultInjector):
    """Wire the injector's message-drop schedule into a SocketTransport
    (its `broadcast` consults `send_filter` per outbound message)."""
    ordinal = {"n": 0}

    def fltr(peer: int) -> bool:
        i = ordinal["n"]
        ordinal["n"] += 1
        return injector.send_filter(peer, i)

    transport.send_filter = fltr
    return transport
