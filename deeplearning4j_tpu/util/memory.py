"""Memory reports: analytic per-layer estimates + exact compiled HBM truth.

Parity target: DL4J `nn/conf/memory/LayerMemoryReport.java:22` and
`NetworkMemoryReport.java` — analytic fixed/variable memory estimation per
layer. The TPU build EXCEEDS the reference here: alongside the analytic
estimate it reports the exact numbers XLA's compiler assigns to the jitted
training step (`jit(...).lower(...).compile().memory_analysis()`), which is
ground truth for HBM on device — something the JVM reference cannot see.

Analytic model (per layer):
    params          = bytes of the layer's parameter leaves
    updater_state   = bytes of the optimizer-state leaves tied to the layer
    activations     = batch x output_type.flat_size x dtype (forward)
    working (train) = 2x activations (forward + gradient wrt activations,
                      the dominant autodiff residency; XLA fuses the rest)
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import jax
import numpy as np


def _tree_bytes(tree) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if not hasattr(leaf, "shape"):
            leaf = np.asarray(leaf)
        total += int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
    return total


@dataclasses.dataclass
class LayerMemoryReport:
    """One layer/vertex row (DL4J LayerMemoryReport analog)."""
    name: str
    layer_type: str
    params_bytes: int
    updater_state_bytes: int
    activation_bytes: int          # inference-time output residency
    working_bytes: int             # training-time (fwd + bwd residual)

    @property
    def total_train_bytes(self) -> int:
        return (self.params_bytes + self.updater_state_bytes +
                self.working_bytes)

    @property
    def total_inference_bytes(self) -> int:
        return self.params_bytes + self.activation_bytes


@dataclasses.dataclass
class NetworkMemoryReport:
    """Whole-network aggregation (DL4J NetworkMemoryReport analog) plus the
    XLA compiled-step truth when available."""
    layers: List[LayerMemoryReport]
    batch_size: int
    input_bytes: int
    compiled: Optional[Dict[str, int]] = None   # exact, from XLA

    @property
    def total_params_bytes(self) -> int:
        return sum(r.params_bytes for r in self.layers)

    @property
    def total_updater_bytes(self) -> int:
        return sum(r.updater_state_bytes for r in self.layers)

    @property
    def total_activation_bytes(self) -> int:
        return sum(r.activation_bytes for r in self.layers)

    @property
    def total_train_bytes(self) -> int:
        """Analytic peak-residency estimate for one training step."""
        return (self.input_bytes + self.total_params_bytes +
                self.total_updater_bytes +
                sum(r.working_bytes for r in self.layers) +
                # gradient buffer the updater consumes (params-sized)
                self.total_params_bytes)

    @property
    def total_inference_bytes(self) -> int:
        return (self.input_bytes + self.total_params_bytes +
                max((r.activation_bytes for r in self.layers), default=0))

    @property
    def compiled_total_bytes(self) -> Optional[int]:
        # one peak-residency formula across memory_report, the program
        # ledger, and bench rows
        from deeplearning4j_tpu.monitor.xla import hbm_peak
        return hbm_peak(self.compiled)

    def summary(self) -> str:
        lines = [f"{'layer':<24}{'type':<22}{'params':>12}{'updater':>12}"
                 f"{'acts':>12}{'train':>12}"]
        for r in self.layers:
            lines.append(f"{r.name:<24}{r.layer_type:<22}"
                         f"{r.params_bytes:>12,}{r.updater_state_bytes:>12,}"
                         f"{r.activation_bytes:>12,}"
                         f"{r.total_train_bytes:>12,}")
        lines.append(f"analytic train total (batch={self.batch_size}): "
                     f"{self.total_train_bytes:,} bytes")
        if self.compiled:
            lines.append(f"XLA compiled step: {self.compiled} "
                         f"(total {self.compiled_total_bytes:,} bytes)")
        return "\n".join(lines)


def _scratch_bytes(layer, in_t, out_t, batch_size, dtype_size) -> int:
    """Layer-specific working scratch beyond activations: convolution
    lowering materializes im2col-style column buffers of
    batch x out_h x out_w x kernel_area x c_in (forward and again for the
    backward pass) — the same term DL4J's ConvolutionLayer memory report
    models as its working memory."""
    kernel = getattr(layer, "kernel", None)
    if kernel is None or len(getattr(out_t, "shape", ())) != 3 \
            or "onvolution" not in type(layer).__name__:
        return 0     # pooling lowers to reduce_window — no col buffer
    out_h, out_w = out_t.shape[0], out_t.shape[1]
    c_in = in_t.shape[2] if len(in_t.shape) == 3 else in_t.features
    col = batch_size * out_h * out_w * kernel[0] * kernel[1] * c_in
    return 2 * col * dtype_size          # forward + backward col buffers


def _split_opt_state_bytes(opt_state, params) -> Dict[str, int]:
    """Bytes of optimizer state attributable to each top-level param key.

    optax state mirrors the params pytree inside each transform's leaves;
    matching on the top-level key structure is enough for per-layer
    attribution (anything unmatchable lands under '__other__')."""
    per_key = {k: 0 for k in params}
    other = 0

    def walk(node):
        nonlocal other
        if isinstance(node, dict) and set(node.keys()) == set(params.keys()):
            for k in node:
                per_key[k] += _tree_bytes(node[k])
            return
        if isinstance(node, (tuple, list)):
            for c in node:
                walk(c)
            return
        if hasattr(node, "_fields"):            # NamedTuple state
            for c in node:
                walk(c)
            return
        if isinstance(node, dict):
            for c in node.values():
                walk(c)
            return
        other += _tree_bytes(node)

    walk(opt_state)
    per_key["__other__"] = other
    return per_key


def build_memory_report(net, batch_size: int,
                        with_compiled: bool = True) -> NetworkMemoryReport:
    """Analytic + compiled memory report for a MultiLayerNetwork or
    ComputationGraph (exposed as net.memory_report(batch_size))."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    if net.params is None:
        raise RuntimeError("init() the network before memory_report()")
    is_graph = isinstance(net, ComputationGraph)
    dtype_size = np.dtype(net._compute_dtype).itemsize
    opt_split = _split_opt_state_bytes(net.opt_state, net.params)

    rows = []
    if is_graph:
        types = net._vertex_types or net._resolve_types()
        net._vertex_types = types
        input_bytes = sum(batch_size * t.flat_size * dtype_size
                          for t in net.conf.input_types)
        for name in net._topo:
            vd = net.conf.vertices[name]
            out_t = types[name]
            in_t = types[vd.inputs[0]]
            act = batch_size * out_t.flat_size * dtype_size
            p_bytes = _tree_bytes(net.params.get(name, {}))
            scratch = _scratch_bytes(vd.vertex, in_t, out_t, batch_size,
                                     dtype_size)
            rows.append(LayerMemoryReport(
                name=name, layer_type=type(vd.vertex).__name__,
                params_bytes=p_bytes,
                updater_state_bytes=opt_split.get(name, 0),
                activation_bytes=act, working_bytes=2 * act + scratch))
    else:
        types = net._resolve_types()     # per-layer INPUT types
        input_bytes = batch_size * net.conf.input_type.flat_size * dtype_size
        for i, layer in enumerate(net.layers):
            out_t = layer.output_type(types[i])
            act = batch_size * out_t.flat_size * dtype_size
            key = str(i)
            scratch = _scratch_bytes(layer, types[i], out_t, batch_size,
                                     dtype_size)
            rows.append(LayerMemoryReport(
                name=key, layer_type=type(layer).__name__,
                params_bytes=_tree_bytes(net.params.get(key, {})),
                updater_state_bytes=opt_split.get(key, 0),
                activation_bytes=act, working_bytes=2 * act + scratch))

    compiled = None
    if with_compiled:
        compiled = _compiled_step_memory(net, batch_size, is_graph)
    return NetworkMemoryReport(layers=rows, batch_size=batch_size,
                               input_bytes=input_bytes, compiled=compiled)


def _read_memory_analysis(compiled):
    """Capability-probe seam: the one call that can legitimately fail on a
    backend without memory_analysis support (tests monkeypatch this to
    simulate such a backend)."""
    return compiled.memory_analysis()


def _count_unavailable():
    """The degraded path is counted, not silent: visible on /metrics as
    xla_analysis_unavailable_total{kind="memory"}."""
    from deeplearning4j_tpu.monitor import xla as xla_ledger
    xla_ledger.analysis_unavailable("memory")


def _compiled_step_memory(net, batch_size, is_graph) -> Optional[Dict[str, int]]:
    """Lower + compile one training step and read XLA's memory analysis.

    Lowering errors propagate (a signature/shape bug here must be loud,
    not reported as a backend limitation); only the memory_analysis
    capability probe itself degrades to None."""
    import logging

    import jax.numpy as jnp
    if is_graph:
        x = tuple(jnp.zeros((batch_size,) + t.shape, net._compute_dtype)
                  for t in net.conf.input_types)
        y = []
        for o in net.conf.network_outputs:
            t = (net._vertex_types or net._resolve_types())[o]
            y.append(jnp.zeros((batch_size,) + t.shape,
                               net._compute_dtype))
        y = tuple(y)
        if net._train_step is None:
            net._train_step = net._make_train_step()
        lowered = net._train_step.lower(
            net.params, net.opt_state, net.state, x, y, None, None,
            jax.random.PRNGKey(0), None)
    else:
        types = net._resolve_types()
        out_t = net.layers[-1].output_type(types[-1])
        x = jnp.zeros((batch_size,) + net.conf.input_type.shape,
                      net._compute_dtype)
        y = jnp.zeros((batch_size,) + out_t.shape, net._compute_dtype)
        step = net._get_train_step(None, None, None)
        lowered = step.lower(net.params, net.opt_state, net.state, x, y,
                             None, None, jax.random.PRNGKey(0), None)
    try:
        ma = _read_memory_analysis(lowered.compile())
    except Exception as e:      # backend without memory_analysis support
        _count_unavailable()
        logging.getLogger("deeplearning4j_tpu").warning(
            "compiled memory analysis unavailable on this backend: %r", e)
        return None
    if ma is None:
        _count_unavailable()
        return None
    # shared attr parsing with the program ledger (one spelling to drift)
    from deeplearning4j_tpu.monitor.xla import hbm_stats
    return hbm_stats(ma)
