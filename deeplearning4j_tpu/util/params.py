"""Canonical flat parameter view.

DL4J stores all network parameters as ONE flat buffer with per-layer views
(MultiLayerNetwork.java:114,603-627) — enabling whole-model averaging,
encoding, and serialization as single-array ops. Here params are a pytree;
these helpers provide the equivalent canonical flattening (deterministic
order: layer key sorted numerically, then param name lexicographically),
used by checkpointing, parameter averaging, and transfer learning.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np


def _sorted_items(tree: dict):
    def keyfn(k):
        try:
            return (0, int(k), "")
        except (TypeError, ValueError):
            return (1, 0, str(k))
    return sorted(tree.items(), key=lambda kv: keyfn(kv[0]))


def iter_leaves(tree, prefix=()):
    """Deterministic (path, leaf) iteration."""
    if isinstance(tree, dict):
        for k, v in _sorted_items(tree):
            yield from iter_leaves(v, prefix + (str(k),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from iter_leaves(v, prefix + (str(i),))
    elif tree is not None:
        yield prefix, tree


def num_params(tree) -> int:
    return int(sum(np.prod(leaf.shape) for _, leaf in iter_leaves(tree)))


def owned_leaf(a, sharding=None):
    """Host/array leaf -> XLA-owned device buffer. jnp.asarray on a numpy
    array can be ZERO-COPY on CPU backends: the jax array aliases
    numpy-owned memory, and DONATING it into a jitted train step
    (donate_argnums) frees/reuses memory XLA does not own — heap
    corruption that surfaces as garbage params or a segfault at a random
    later point (the historical serde-resume / keras-import crash
    class). copy=True forces a buffer XLA owns outright.

    `sharding` (the GSPMD-plan variant of the same contract): the owned
    copy is additionally placed on the given jax.sharding.Sharding.
    Device-resident leaves copy first (preserves committed shardings;
    the device_put is an identity when already placed). HOST leaves on a
    non-CPU backend go straight through device_put — H2D is itself an
    owning copy (host memory can never alias the device arena) and each
    device receives only ITS shard's slice, so restoring a model that
    only fits sharded never materializes whole arrays on one chip. On
    the CPU backend "device" memory IS host memory — there zero-copy
    aliasing is the PR-3 trap, so the explicit owned copy happens first
    (and the transient whole-array copy is free: it's RAM either way)."""
    if sharding is None:
        return jnp.array(a, copy=True)
    if isinstance(a, jax.Array) or jax.default_backend() == "cpu":
        return jax.device_put(jnp.array(a, copy=True), sharding)
    return jax.device_put(a, sharding)


def own_tree(tree, shardings=None):
    """owned_leaf over a whole pytree (params / optimizer state / layer
    state). Called once at every fit() entry so that params assigned from
    ANY host source (checkpoint restore, keras/dl4j import,
    set_params_flat, user numpy) are safe to donate — one extra copy per
    fit call, not per step.

    `shardings`: optional congruent pytree of Shardings (a ShardingPlan's
    param_shardings/opt_shardings) — restored host arrays land laundered
    AND placed in one pass, so a checkpoint resumed under a plan never
    runs a step on misplaced (or heap-aliased) leaves."""
    if shardings is None:
        return jax.tree_util.tree_map(owned_leaf, tree)
    return jax.tree_util.tree_map(
        lambda a, s: None if a is None else owned_leaf(a, s),
        tree, shardings, is_leaf=lambda x: x is None)


def params_to_flat(tree) -> jnp.ndarray:
    """Flatten a param pytree to one 1-D vector in canonical order."""
    leaves = [jnp.ravel(leaf) for _, leaf in iter_leaves(tree)]
    if not leaves:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate(leaves)


def flat_to_params(flat, template):
    """Inverse of params_to_flat given a template pytree with shapes/dtypes."""
    rebuilt = _clone_structure(template)
    offset = 0

    def assign(tree, path, value):
        node = tree
        for p in path[:-1]:
            node = node[int(p)] if isinstance(node, list) else node[p]
        last = path[-1]
        if isinstance(node, list):
            node[int(last)] = value
        else:
            node[last] = value

    for path, leaf in iter_leaves(template):
        size = int(np.prod(leaf.shape))
        chunk = flat[offset:offset + size].reshape(leaf.shape).astype(leaf.dtype)
        assign(rebuilt, path, chunk)
        offset += size
    return rebuilt


def _clone_structure(tree):
    if isinstance(tree, dict):
        return {k: _clone_structure(v) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        return [_clone_structure(v) for v in tree]
    return None


def format_param_table(rows, total: int) -> str:
    """Fixed-width table for summary() (shared by both containers).
    rows[0] is the header; appends a total-parameters footer."""
    widths = [max(len(r[c]) for r in rows) for c in range(len(rows[0]))]
    gap = 2 * (len(widths) - 1)
    lines = ["  ".join(v.ljust(w) for v, w in zip(r, widths)) for r in rows]
    lines.insert(1, "-" * (sum(widths) + gap))
    lines.append(f"total parameters: {total:,}")
    return "\n".join(lines)
