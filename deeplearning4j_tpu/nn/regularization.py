"""Dropout variants, weight noise, and parameter constraints.

Parity targets:
- `nn/conf/dropout/{Dropout,AlphaDropout,GaussianDropout,GaussianNoise}.java`
- `nn/conf/weightnoise/{DropConnect,WeightNoise}.java`
- `nn/conf/constraint/{MaxNormConstraint,MinMaxNormConstraint,
  NonNegativeConstraint,UnitNormConstraint}.java` (applied post-update via
  `BaseConstraint.applyConstraint`)

Wiring (TPU-native): a LayerConf's `dropout` field takes a float (plain
inverted dropout, the DL4J default) or one of the IDropout objects below;
`weight_noise` transforms the layer's weight params inside the training
forward (DL4J `getParamWithNoise`); `constraints` are projected onto the
params right after the optimizer update inside the SAME jit-compiled train
step — no extra device round-trip.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.base import register_layer


# ------------------------------------------------------------ dropout family
@dataclasses.dataclass(frozen=True)
class IDropout:
    """Base input-dropout schedule; subclasses implement apply()."""

    def apply(self, x, rng):
        raise NotImplementedError


@register_layer
@dataclasses.dataclass(frozen=True)
class Dropout(IDropout):
    """Standard inverted dropout (nn/conf/dropout/Dropout.java)."""
    p: float = 0.5          # DROP probability (DL4J stores keep prob; the
    # float-valued LayerConf.dropout field keeps DL4J's semantics — this
    # object form uses drop probability like every modern framework)

    def apply(self, x, rng):
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


@register_layer
@dataclasses.dataclass(frozen=True)
class AlphaDropout(IDropout):
    """SELU-preserving dropout (nn/conf/dropout/AlphaDropout.java):
    dropped units are set to alpha' and the result is affinely corrected so
    self-normalizing activations keep zero mean / unit variance."""
    p: float = 0.05

    # fixed-point constants of SELU (Klambauer et al.)
    _ALPHA = 1.6732632423543772
    _SCALE = 1.0507009873554805

    def apply(self, x, rng):
        keep = 1.0 - self.p
        alpha_p = -self._ALPHA * self._SCALE          # value dropped units take
        a = (keep + alpha_p ** 2 * keep * (1 - keep)) ** -0.5
        b = -a * alpha_p * (1 - keep)
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return a * jnp.where(mask, x, alpha_p) + b


@register_layer
@dataclasses.dataclass(frozen=True)
class GaussianDropout(IDropout):
    """Multiplicative Gaussian noise N(1, rate/(1-rate))
    (nn/conf/dropout/GaussianDropout.java)."""
    rate: float = 0.1

    def apply(self, x, rng):
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        return x * (1.0 + std * jax.random.normal(rng, x.shape, x.dtype))


@register_layer
@dataclasses.dataclass(frozen=True)
class GaussianNoise(IDropout):
    """Additive Gaussian noise (nn/conf/dropout/GaussianNoise.java)."""
    stddev: float = 0.1

    def apply(self, x, rng):
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype)


@register_layer
@dataclasses.dataclass(frozen=True)
class SpatialDropout(IDropout):
    """Channel-wise dropout (nn/conf/dropout/SpatialDropout.java; Keras
    SpatialDropout1D/2D): drops entire feature maps — one Bernoulli draw
    per (example, channel), broadcast over the spatial/time axes. The
    channel axis is last (NHWC / (B, T, C) layouts)."""
    p: float = 0.5

    def apply(self, x, rng):
        keep = 1.0 - self.p
        shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        mask = jax.random.bernoulli(rng, keep, shape)
        return jnp.where(mask, x / keep, 0.0)


def apply_input_dropout(dropout, x, train, rng):
    """Dispatch for LayerConf.dropout: float (DL4J drop-prob semantics) or
    IDropout object. Called from LayerConf.maybe_dropout_input."""
    if not train or rng is None or dropout is None:
        return x
    if isinstance(dropout, IDropout):
        return dropout.apply(x, rng)
    p = float(dropout)
    if p <= 0.0:
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


# -------------------------------------------------------- weight noise family
@dataclasses.dataclass(frozen=True)
class IWeightNoise:
    apply_to_bias: bool = False

    def transform(self, params: dict, rng):
        """Returns a transformed COPY of the layer's params for this forward
        (DL4J BaseLayer.getParamWithNoise)."""
        out = {}
        for i, (k, v) in enumerate(sorted(params.items())):
            if k.startswith("b") and not self.apply_to_bias:
                out[k] = v
            else:
                out[k] = self._transform_one(v, jax.random.fold_in(rng, i))
        return out

    def _transform_one(self, w, rng):
        raise NotImplementedError


@register_layer
@dataclasses.dataclass(frozen=True)
class DropConnect(IWeightNoise):
    """Randomly zero WEIGHTS during training (nn/conf/weightnoise/
    DropConnect.java); inverted scaling keeps the expectation."""
    p: float = 0.5          # drop probability

    def _transform_one(self, w, rng):
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, w.shape)
        return jnp.where(mask, w / keep, 0.0)


@register_layer
@dataclasses.dataclass(frozen=True)
class WeightNoise(IWeightNoise):
    """Additive or multiplicative Gaussian weight noise
    (nn/conf/weightnoise/WeightNoise.java)."""
    stddev: float = 0.05
    additive: bool = True

    def _transform_one(self, w, rng):
        noise = jax.random.normal(rng, w.shape, w.dtype) * self.stddev
        return w + noise if self.additive else w * (1.0 + noise)


def apply_weight_noise(layer, params, train, rng):
    """Network-forward hook: transform a layer's params when training."""
    noise = getattr(layer, "weight_noise", None)
    if not train or rng is None or noise is None:
        return params
    return noise.transform(params, rng)


# ---------------------------------------------------------- constraint family
@dataclasses.dataclass(frozen=True)
class BaseConstraint:
    """Projection applied to weight params right after the optimizer update
    (DL4J BaseConstraint.applyConstraint; StochasticGradientDescent calls
    applyConstraints post-step). `apply_to_bias` mirrors DL4J's
    constrainBias flag."""
    apply_to_bias: bool = False

    def project(self, w):
        raise NotImplementedError

    def _norms(self, w):
        """L2 norm per output unit: all axes except the last (fan-in /
        spatial dims for conv HWIO kernels — DL4J getBroadcastDims)."""
        axes = tuple(range(w.ndim - 1)) or (0,)
        return jnp.sqrt(jnp.sum(w * w, axis=axes, keepdims=True)), axes


@register_layer
@dataclasses.dataclass(frozen=True)
class MaxNormConstraint(BaseConstraint):
    """Clip each output unit's weight-vector L2 norm to max_norm
    (nn/conf/constraint/MaxNormConstraint.java)."""
    max_norm: float = 2.0

    def project(self, w):
        norms, _ = self._norms(w)
        scale = jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-12))
        return w * scale


@register_layer
@dataclasses.dataclass(frozen=True)
class MinMaxNormConstraint(BaseConstraint):
    """Force norms into [min, max], interpolated by rate
    (nn/conf/constraint/MinMaxNormConstraint.java)."""
    min_norm: float = 0.0
    max_norm: float = 2.0
    rate: float = 1.0

    def project(self, w):
        norms, _ = self._norms(w)
        clipped = jnp.clip(norms, self.min_norm, self.max_norm)
        target = self.rate * clipped + (1.0 - self.rate) * norms
        return w * target / jnp.maximum(norms, 1e-12)


@register_layer
@dataclasses.dataclass(frozen=True)
class NonNegativeConstraint(BaseConstraint):
    """Project weights onto the non-negative orthant
    (nn/conf/constraint/NonNegativeConstraint.java)."""

    def project(self, w):
        return jnp.maximum(w, 0.0)


@register_layer
@dataclasses.dataclass(frozen=True)
class UnitNormConstraint(BaseConstraint):
    """Rescale each output unit's weight vector to unit L2 norm
    (nn/conf/constraint/UnitNormConstraint.java)."""

    def project(self, w):
        norms, _ = self._norms(w)
        return w / jnp.maximum(norms, 1e-12)


def apply_constraints(layer_map, params):
    """Post-update projection for every constrained layer.

    layer_map: {param-dict key: LayerConf}; params: the full network params
    pytree. Runs INSIDE the jit-compiled train step (pure function)."""
    new_params = dict(params)
    for key, layer in layer_map.items():
        cons: Tuple = getattr(layer, "constraints", ()) or ()
        if not cons or key not in new_params:
            continue
        lp = dict(new_params[key])
        for pname, w in lp.items():
            if not hasattr(w, "ndim"):
                continue
            for c in cons:
                if pname.startswith("b") and not c.apply_to_bias:
                    continue
                lp[pname] = c.project(lp[pname])
        new_params[key] = lp
    return new_params


def has_constraints(layers) -> bool:
    return any(getattr(l, "constraints", ()) for l in layers)


def constraint_map(model) -> dict:
    """{param-dict key: LayerConf} for `apply_constraints`, for either
    container — the ONE construction every trainer (container train
    steps, ParallelWrapper, context/pipeline trainers) shares. Graph keys
    are vertex names; MultiLayerNetwork keys are layer indices as
    strings, matching the params pytree layout."""
    from deeplearning4j_tpu.nn.conf.base import LayerConf
    conf = getattr(model, "conf", None)
    vertices = getattr(conf, "vertices", None)
    if vertices is not None:
        return {name: vd.vertex for name, vd in vertices.items()
                if isinstance(vd.vertex, LayerConf)}
    return {str(i): l for i, l in enumerate(model.layers)}
