"""Graph vertices — parameter-free DAG combinators.

Parity target: DL4J nn/conf/graph/ (14 vertex types) + impls in
nn/graph/vertex/impl/: Merge, ElementWise(Add/Sub/Mul/Max/Avg), Subset,
Stack, Unstack, Reshape, Scale, Shift, L2Normalize, L2 (pairwise distance),
LastTimeStep, DuplicateToTimeSeries, ReverseTimeSeries, Preprocessor.

Each vertex is a frozen dataclass with `output_type(*input_types)` and
`apply(*inputs)` — pure functions XLA fuses into the surrounding graph.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.base import InputType, Kind, register_layer


@dataclasses.dataclass(frozen=True)
class GraphVertexConf:
    def output_type(self, *input_types: InputType) -> InputType:
        raise NotImplementedError

    def apply(self, *inputs):
        raise NotImplementedError

    def has_params(self) -> bool:
        return False


@register_layer
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertexConf):
    """Concatenate along the feature axis (DL4J MergeVertex)."""

    def output_type(self, *input_types: InputType) -> InputType:
        k = input_types[0].kind
        if k == Kind.FF:
            return InputType.feed_forward(sum(t.shape[0] for t in input_types))
        if k == Kind.RNN:
            t0 = input_types[0].shape[0]
            return InputType(Kind.RNN, (t0, sum(t.shape[1] for t in input_types)))
        if k == Kind.CNN:
            h, w, _ = input_types[0].shape
            return InputType.convolutional(h, w, sum(t.shape[2] for t in input_types))
        raise ValueError(k)

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=-1)


@register_layer
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertexConf):
    """Pointwise combine (DL4J ElementWiseVertex): add|subtract|product|max|average."""
    op: str = "add"

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            return inputs[0] - inputs[1]
        if op in ("product", "mul"):
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        if op in ("average", "avg"):
            return sum(inputs) / float(len(inputs))
        raise ValueError(f"Unknown ElementWise op {self.op}")


@register_layer
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertexConf):
    """Feature-range slice [from_idx, to_idx] inclusive (DL4J SubsetVertex)."""
    from_idx: int = 0
    to_idx: int = 0

    def output_type(self, *input_types: InputType) -> InputType:
        n = self.to_idx - self.from_idx + 1
        t = input_types[0]
        if t.kind == Kind.FF:
            return InputType.feed_forward(n)
        if t.kind == Kind.RNN:
            return InputType(Kind.RNN, (t.shape[0], n))
        if t.kind == Kind.CNN:
            return InputType.convolutional(t.shape[0], t.shape[1], n)
        raise ValueError(t.kind)

    def apply(self, *inputs):
        return inputs[0][..., self.from_idx:self.to_idx + 1]


@register_layer
@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertexConf):
    """Stack along batch dim (DL4J StackVertex)."""

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        return jnp.concatenate(inputs, axis=0)


@register_layer
@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertexConf):
    """Take batch slice `from_idx` of `stack_size` (DL4J UnstackVertex)."""
    from_idx: int = 0
    stack_size: int = 1

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_idx * n:(self.from_idx + 1) * n]


@register_layer
@dataclasses.dataclass(frozen=True)
class ReshapeVertex(GraphVertexConf):
    """Reshape (batch-preserving) (DL4J ReshapeVertex). new_shape excludes batch."""
    new_shape: Tuple[int, ...] = ()
    kind: str = "ff"

    def output_type(self, *input_types: InputType) -> InputType:
        return InputType(Kind(self.kind), tuple(self.new_shape))

    def apply(self, *inputs):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.new_shape))


@register_layer
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertexConf):
    scale: float = 1.0

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        return inputs[0] * self.scale


@register_layer
@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertexConf):
    shift: float = 0.0

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        return inputs[0] + self.shift


@register_layer
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertexConf):
    eps: float = 1e-8

    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        x = inputs[0]
        norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
        return x / jnp.maximum(norm, self.eps)


@register_layer
@dataclasses.dataclass(frozen=True)
class L2Vertex(GraphVertexConf):
    """Pairwise L2 distance between two inputs (DL4J L2Vertex)."""
    eps: float = 1e-8

    def output_type(self, *input_types: InputType) -> InputType:
        return InputType.feed_forward(1)

    def apply(self, *inputs):
        a, b = inputs
        d = a.reshape(a.shape[0], -1) - b.reshape(b.shape[0], -1)
        return jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + self.eps)


@register_layer
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertexConf):
    """(B,T,F) -> (B,F) last step (DL4J LastTimeStepVertex); mask-aware
    variant lives in the LastTimeStep layer wrapper."""

    def output_type(self, *input_types: InputType) -> InputType:
        return InputType.feed_forward(input_types[0].shape[1])

    def apply(self, *inputs):
        return inputs[0][:, -1, :]


@register_layer
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertexConf):
    """(B,F) -> (B,T,F) by repetition; T taken from a reference input
    (DL4J DuplicateToTimeSeriesVertex)."""

    def output_type(self, *input_types: InputType) -> InputType:
        ff, ref = input_types
        return InputType(Kind.RNN, (ref.shape[0], ff.shape[0]))

    def apply(self, *inputs):
        x, ref = inputs
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], ref.shape[1], x.shape[1]))


@register_layer
@dataclasses.dataclass(frozen=True)
class ReverseTimeSeriesVertex(GraphVertexConf):
    def output_type(self, *input_types: InputType) -> InputType:
        return input_types[0]

    def apply(self, *inputs):
        return jnp.flip(inputs[0], axis=1)


@register_layer
@dataclasses.dataclass(frozen=True)
class PoolHelperVertex(GraphVertexConf):
    """Strip the first spatial row and column of a CNN activation
    (DL4J nn/conf/graph/PoolHelperVertex.java + impl
    nn/graph/vertex/impl/PoolHelperVertex.java) — compensates the
    off-by-one pooling of Caffe-imported GoogLeNet-style models."""

    def output_type(self, *input_types: InputType) -> InputType:
        t = input_types[0]
        if t.kind != Kind.CNN:
            raise ValueError("PoolHelperVertex expects CNN input, got "
                             f"{t.kind}")
        h, w, c = t.shape
        return InputType.convolutional(h - 1, w - 1, c)

    def apply(self, *inputs):
        return inputs[0][:, 1:, 1:, :]     # NHWC: drop first row + column
