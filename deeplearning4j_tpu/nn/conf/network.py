"""Network-level configuration: sequential and DAG configs + fluent builders.

Parity targets:
- NeuralNetConfiguration.Builder -> ListBuilder
  (DL4J NeuralNetConfiguration.java:584 builder, :744 list()) — global
  defaults (seed, updater, weight init, activation, l1/l2) applied to layers
  that don't override them.
- MultiLayerConfiguration with toJson/fromJson
  (MultiLayerConfiguration.java:120,138) — JSON round-trip is the wire format
  for model replication and the checkpoint config entry.
- ComputationGraphConfiguration.GraphBuilder
  (ComputationGraphConfiguration.java; graph vertices in nn/conf/graph/).
- BackpropType.TruncatedBPTT with fwd/bwd lengths
  (MultiLayerNetwork.java:1315-1317).

TPU-native additions (no DL4J analog): `dtype`/`compute_dtype` for bf16
mixed-precision on the MXU.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu.nn.conf.base import (
    InputType, LayerConf, layer_from_dict, layer_to_dict,
)
from deeplearning4j_tpu.nn.updaters import Sgd, get_updater


@dataclasses.dataclass(frozen=True)
class MultiLayerConfiguration:
    layers: Tuple[LayerConf, ...] = ()
    input_type: Optional[InputType] = None
    seed: int = 0
    updater: Any = dataclasses.field(default_factory=lambda: Sgd(1e-2))
    backprop_type: str = "standard"       # standard | tbptt
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    dtype: str = "float32"                # parameter dtype
    compute_dtype: Optional[str] = None   # activation dtype (None = dtype)
    grad_clip_norm: Optional[float] = None
    grad_clip_value: Optional[float] = None
    # rematerialize per-layer activations in the backward pass
    # (jax.checkpoint): trades recompute FLOPs for HBM — the TPU lever
    # for deep nets / long sequences that don't fit otherwise
    gradient_checkpointing: bool = False

    # ---- serde ----------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "format": "deeplearning4j_tpu.MultiLayerConfiguration.v1",
            "layers": [layer_to_dict(l) for l in self.layers],
            "input_type": None if self.input_type is None else self.input_type.to_dict(),
            "seed": self.seed,
            "updater": layer_to_dict(get_updater(self.updater)),
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "grad_clip_norm": self.grad_clip_norm,
            "grad_clip_value": self.grad_clip_value,
            "gradient_checkpointing": self.gradient_checkpointing,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def to_yaml(self) -> str:
        """YAML form (DL4J MultiLayerConfiguration.toYaml)."""
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=tuple(layer_from_dict(l) for l in d["layers"]),
            input_type=None if d.get("input_type") is None
            else InputType.from_dict(d["input_type"]),
            seed=d.get("seed", 0),
            updater=layer_from_dict(d["updater"]) if isinstance(d.get("updater"), dict)
            else d.get("updater", Sgd(1e-2)),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            grad_clip_norm=d.get("grad_clip_norm"),
            grad_clip_value=d.get("grad_clip_value"),
            gradient_checkpointing=d.get("gradient_checkpointing", False),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))


class NeuralNetConfiguration:
    """Fluent builder entry point, mirroring DL4J usage:

        conf = (NeuralNetConfiguration.Builder()
                .seed(12345).updater(Adam(1e-3))
                .list()
                .layer(DenseLayer(n_out=128, activation="relu"))
                .layer(OutputLayer(n_out=10))
                .set_input_type(InputType.feed_forward(784))
                .build())
    """

    class Builder:
        def __init__(self):
            self._seed = 0
            self._updater: Any = Sgd(1e-2)
            self._l1 = 0.0
            self._l2 = 0.0
            self._dtype = "float32"
            self._compute_dtype: Optional[str] = None
            self._grad_clip_norm: Optional[float] = None
            self._grad_clip_value: Optional[float] = None
            self._gradient_checkpointing = False
            self._weight_init: Optional[str] = None
            self._activation: Optional[str] = None
            self._dropout: Optional[float] = None

        def seed(self, s: int):
            self._seed = int(s)
            return self

        def updater(self, u):
            self._updater = u
            return self

        def l1(self, v: float):
            self._l1 = float(v)
            return self

        def l2(self, v: float):
            self._l2 = float(v)
            return self

        def weight_init(self, w: str):
            self._weight_init = w
            return self

        def activation(self, a: str):
            self._activation = a
            return self

        def dropout(self, d: float):
            self._dropout = float(d)
            return self

        def dtype(self, d: str):
            self._dtype = d
            return self

        def compute_dtype(self, d: str):
            self._compute_dtype = d
            return self

        def grad_clip_norm(self, v: float):
            self._grad_clip_norm = float(v)
            return self

        def grad_clip_value(self, v: float):
            self._grad_clip_value = float(v)
            return self

        def gradient_checkpointing(self, on: bool = True):
            self._gradient_checkpointing = bool(on)
            return self

        def list(self) -> "ListBuilder":
            return ListBuilder(self)

        def graph_builder(self) -> "GraphBuilder":
            return GraphBuilder(self)

    def _apply_defaults(builder: "NeuralNetConfiguration.Builder",
                        layer: LayerConf) -> LayerConf:
        raise NotImplementedError


def _apply_global_defaults(b: "NeuralNetConfiguration.Builder",
                           layer: LayerConf) -> LayerConf:
    """Fill layer fields from global builder defaults when the layer left
    them at their dataclass defaults (DL4J's 'global config' semantics)."""
    updates: Dict[str, Any] = {}
    fields = {f.name: f for f in dataclasses.fields(layer)}
    if b._l1 and "l1" in fields and layer.l1 == 0.0:
        updates["l1"] = b._l1
    if b._l2 and "l2" in fields and layer.l2 == 0.0:
        updates["l2"] = b._l2
    if b._dropout is not None and layer.dropout == 0.0:
        updates["dropout"] = b._dropout
    if b._weight_init is not None and "weight_init" in fields:
        f = fields["weight_init"]
        if getattr(layer, "weight_init") == f.default:
            updates["weight_init"] = b._weight_init
    if b._activation is not None and "activation" in fields:
        f = fields["activation"]
        if getattr(layer, "activation") == f.default:
            updates["activation"] = b._activation
    return dataclasses.replace(layer, **updates) if updates else layer


class ListBuilder:
    """DL4J NeuralNetConfiguration.ListBuilder analog."""

    def __init__(self, parent: "NeuralNetConfiguration.Builder"):
        self._parent = parent
        self._layers: List[LayerConf] = []
        self._input_type: Optional[InputType] = None
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def layer(self, l: LayerConf):
        self._layers.append(_apply_global_defaults(self._parent, l))
        return self

    def set_input_type(self, t: InputType):
        self._input_type = t
        return self

    def backprop_type(self, t: str, fwd_length: int = 20, back_length: int = 20):
        if t == "tbptt" and back_length != fwd_length:
            # DL4J allows tBPTTBackwardLength < forward; this framework chunks
            # by one length (gradients truncate at chunk boundaries). Refuse
            # rather than silently ignoring the shorter backward window.
            raise NotImplementedError(
                "tbptt_back_length != tbptt_fwd_length is not supported; "
                "use equal lengths (gradient truncation happens at chunk "
                "boundaries of fwd_length)")
        self._backprop_type = t
        self._tbptt_fwd = fwd_length
        self._tbptt_back = back_length
        return self

    def tbptt(self, fwd_length: int, back_length: Optional[int] = None):
        return self.backprop_type("tbptt", fwd_length, back_length or fwd_length)

    def build(self) -> MultiLayerConfiguration:
        p = self._parent
        return MultiLayerConfiguration(
            layers=tuple(self._layers),
            input_type=self._input_type,
            seed=p._seed,
            updater=p._updater,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            dtype=p._dtype,
            compute_dtype=p._compute_dtype,
            grad_clip_norm=p._grad_clip_norm,
            grad_clip_value=p._grad_clip_value,
            gradient_checkpointing=p._gradient_checkpointing,
        )


# ------------------------------------------------------------------- graph
@dataclasses.dataclass(frozen=True)
class VertexDef:
    """One node in the DAG: either a LayerConf or a GraphVertex op."""
    vertex: Any                      # LayerConf | GraphVertexConf
    inputs: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class ComputationGraphConfiguration:
    """DAG config (DL4J ComputationGraphConfiguration). Vertices keyed by
    name; topological order computed at build time (ComputationGraph.java:152,401)."""
    vertices: Dict[str, VertexDef] = dataclasses.field(default_factory=dict)
    network_inputs: Tuple[str, ...] = ()
    network_outputs: Tuple[str, ...] = ()
    input_types: Tuple[InputType, ...] = ()
    seed: int = 0
    updater: Any = dataclasses.field(default_factory=lambda: Sgd(1e-2))
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    dtype: str = "float32"
    compute_dtype: Optional[str] = None
    grad_clip_norm: Optional[float] = None
    grad_clip_value: Optional[float] = None
    gradient_checkpointing: bool = False   # remat per-vertex activations

    def topological_order(self) -> List[str]:
        order: List[str] = []
        seen = set(self.network_inputs)
        pending = dict(self.vertices)
        while pending:
            progressed = False
            for name, vd in list(pending.items()):
                if all(i in seen for i in vd.inputs):
                    order.append(name)
                    seen.add(name)
                    del pending[name]
                    progressed = True
            if not progressed:
                raise ValueError(f"Graph has a cycle or missing inputs: {list(pending)}")
        return order

    def to_dict(self) -> dict:
        return {
            "format": "deeplearning4j_tpu.ComputationGraphConfiguration.v1",
            "vertices": {
                name: {"vertex": layer_to_dict(vd.vertex), "inputs": list(vd.inputs)}
                for name, vd in self.vertices.items()
            },
            "network_inputs": list(self.network_inputs),
            "network_outputs": list(self.network_outputs),
            "input_types": [t.to_dict() for t in self.input_types],
            "seed": self.seed,
            "updater": layer_to_dict(get_updater(self.updater)),
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "grad_clip_norm": self.grad_clip_norm,
            "grad_clip_value": self.grad_clip_value,
            "gradient_checkpointing": self.gradient_checkpointing,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def to_yaml(self) -> str:
        """YAML form (DL4J ComputationGraphConfiguration.toYaml)."""
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration(
            vertices={
                name: VertexDef(layer_from_dict(vd["vertex"]), tuple(vd["inputs"]))
                for name, vd in d["vertices"].items()
            },
            network_inputs=tuple(d["network_inputs"]),
            network_outputs=tuple(d["network_outputs"]),
            input_types=tuple(InputType.from_dict(t) for t in d.get("input_types", [])),
            seed=d.get("seed", 0),
            updater=layer_from_dict(d["updater"]) if isinstance(d.get("updater"), dict)
            else d.get("updater", Sgd(1e-2)),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
            dtype=d.get("dtype", "float32"),
            compute_dtype=d.get("compute_dtype"),
            grad_clip_norm=d.get("grad_clip_norm"),
            grad_clip_value=d.get("grad_clip_value"),
            gradient_checkpointing=d.get("gradient_checkpointing", False),
        )

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))


class GraphBuilder:
    """DL4J ComputationGraphConfiguration.GraphBuilder analog."""

    def __init__(self, parent: Optional["NeuralNetConfiguration.Builder"] = None):
        self._parent = parent or NeuralNetConfiguration.Builder()
        self._vertices: Dict[str, VertexDef] = {}
        self._inputs: Tuple[str, ...] = ()
        self._outputs: Tuple[str, ...] = ()
        self._input_types: Tuple[InputType, ...] = ()
        self._backprop_type = "standard"
        self._tbptt_fwd = 20
        self._tbptt_back = 20

    def add_inputs(self, *names: str):
        self._inputs = tuple(names)
        return self

    def set_input_types(self, *types: InputType):
        self._input_types = tuple(types)
        return self

    def add_layer(self, name: str, layer: LayerConf, *inputs: str):
        self._vertices[name] = VertexDef(
            _apply_global_defaults(self._parent, layer), tuple(inputs))
        return self

    def add_vertex(self, name: str, vertex, *inputs: str):
        self._vertices[name] = VertexDef(vertex, tuple(inputs))
        return self

    def set_outputs(self, *names: str):
        self._outputs = tuple(names)
        return self

    def backprop_type(self, t: str, fwd_length: int = 20, back_length: int = 20):
        self._backprop_type = t
        self._tbptt_fwd = fwd_length
        self._tbptt_back = back_length
        return self

    def build(self) -> ComputationGraphConfiguration:
        p = self._parent
        return ComputationGraphConfiguration(
            vertices=dict(self._vertices),
            network_inputs=self._inputs,
            network_outputs=self._outputs,
            input_types=self._input_types,
            seed=p._seed,
            updater=p._updater,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            dtype=p._dtype,
            compute_dtype=p._compute_dtype,
            grad_clip_norm=p._grad_clip_norm,
            grad_clip_value=p._grad_clip_value,
            gradient_checkpointing=p._gradient_checkpointing,
        )
