"""Layer-configuration base machinery.

Capability parity with DL4J's declarative config layer
(deeplearning4j-nn/.../nn/conf/ — NeuralNetConfiguration.java:584 builders,
polymorphic JSON serde in nn/conf/serde/). Differences by design:

- A layer config here is a frozen dataclass that *also carries the math*
  (`init`/`apply` pure functions) instead of DL4J's conf-class/impl-class
  split — in JAX the "implementation" is a pure function, so a separate
  stateful Layer object would add nothing.
- Shape inference uses `InputType` exactly like DL4J's
  `nn/conf/inputs/InputType.java`; preprocessors between mismatched layer
  kinds are auto-inserted like
  `MultiLayerConfiguration.Builder.setInputType` does.
- Serde is a simple `{"@class": <registered name>, ...fields}` scheme —
  the analog of Jackson's polymorphic type info — so configs round-trip
  through JSON (the wire format used for model replication and checkpoints,
  DL4J MultiLayerConfiguration.java:120,138).

Layout conventions are TPU-native: CNN activations are NHWC (DL4J is NCHW),
RNN activations are (batch, time, features) (DL4J is (batch, features, time)).
"""
from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


# ----------------------------------------------------------------- InputType
class Kind(str, enum.Enum):
    FF = "ff"          # (features,)
    CNN = "cnn"        # (height, width, channels) NHWC
    CNN1D = "cnn1d"    # (time, channels)
    RNN = "rnn"        # (time, features)


@dataclasses.dataclass(frozen=True)
class InputType:
    """Shape metadata for one activation tensor, batch dim excluded.

    Mirrors DL4J nn/conf/inputs/InputType (feedForward / convolutional /
    recurrent), with the CNN layout fixed to NHWC.
    """
    kind: Kind
    shape: Tuple[int, ...]

    @staticmethod
    def feed_forward(n: int) -> "InputType":
        return InputType(Kind.FF, (int(n),))

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(Kind.CNN, (int(height), int(width), int(channels)))

    @staticmethod
    def recurrent(features: int, timesteps: int) -> "InputType":
        return InputType(Kind.RNN, (int(timesteps), int(features)))

    @property
    def features(self) -> int:
        """Per-step / per-pixel feature count (DL4J getSize-ish)."""
        if self.kind == Kind.FF:
            return self.shape[0]
        if self.kind in (Kind.RNN, Kind.CNN1D):
            return self.shape[1]
        return self.shape[2]

    @property
    def flat_size(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def to_dict(self):
        return {"kind": self.kind.value, "shape": list(self.shape)}

    @staticmethod
    def from_dict(d):
        return InputType(Kind(d["kind"]), tuple(d["shape"]))


# ------------------------------------------------------------- serde registry
_LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    """Class decorator: register a LayerConf subclass for polymorphic serde
    (the analog of Jackson subtype registration in DL4J nn/conf/serde/)."""
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def _encode_value(v):
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        d = {"@class": type(v).__name__}
        for f in dataclasses.fields(v):
            d[f.name] = _encode_value(getattr(v, f.name))
        return d
    if isinstance(v, enum.Enum):
        return v.value
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    return v


def layer_to_dict(layer) -> dict:
    return _encode_value(layer)


def _decode_fields(cls, d: dict):
    kwargs = {}
    hints = typing.get_type_hints(cls)
    for f in dataclasses.fields(cls):
        if f.name not in d:
            continue
        v = d[f.name]
        if isinstance(v, dict) and "@class" in v:
            v = layer_from_dict(v)
        elif isinstance(v, list):
            v = tuple(layer_from_dict(x) if isinstance(x, dict) and "@class" in x else x
                      for x in v)
            hint = hints.get(f.name)
            origin = typing.get_origin(hint)
            if origin in (list,):
                v = list(v)
        kwargs[f.name] = v
    return cls(**kwargs)


def layer_from_dict(d: dict):
    name = d.get("@class")
    if name is None:
        raise ValueError(f"Missing @class in layer dict: {list(d)[:8]}")
    # Updaters/schedules are dataclasses registered in their own modules.
    cls = _LAYER_REGISTRY.get(name) or _AUX_REGISTRY.get(name)
    if cls is None:
        # registration happens at module import; a standalone
        # load_model() may deserialize before any layer module was
        # imported — pull in the registration packages once and retry
        import deeplearning4j_tpu.nn.layers  # noqa: F401
        import deeplearning4j_tpu.nn.conf.graph_vertices  # noqa: F401
        import deeplearning4j_tpu.nn.regularization  # noqa: F401
        cls = _LAYER_REGISTRY.get(name) or _AUX_REGISTRY.get(name)
    if cls is None:
        raise ValueError(f"Unknown layer/config class '{name}'")
    return _decode_fields(cls, d)


# updaters & schedules participate in the same serde
_AUX_REGISTRY: Dict[str, type] = {}


def _register_aux_dataclasses():
    from deeplearning4j_tpu.nn import updaters as U
    for name in dir(U):
        obj = getattr(U, name)
        if isinstance(obj, type) and dataclasses.is_dataclass(obj):
            _AUX_REGISTRY[obj.__name__] = obj


_register_aux_dataclasses()


# ---------------------------------------------------------------- LayerConf
@dataclasses.dataclass(frozen=True)
class LayerConf:
    """Base class for all layer configurations.

    Subclasses implement:
      output_type(input_type)          shape inference (DL4J Layer.getOutputType)
      init(key, input_type, dtype)     -> (params, state) dicts
      apply(params, state, x, ...)     -> (y, new_state) pure forward
    Backprop is jax.grad through `apply` (DL4J's hand-written
    backpropGradient has no analog; gradient checks are the oracle).
    """
    name: Optional[str] = None
    dropout: Any = 0.0          # input dropout: probability float, or an
    # IDropout object (AlphaDropout/GaussianDropout/GaussianNoise,
    # nn/regularization.py — DL4J nn/conf/dropout/)
    l1: float = 0.0             # L1 regularization coefficient on weights
    l2: float = 0.0             # L2 regularization coefficient on weights
    updater: Optional[Any] = None   # per-layer updater override (DL4J .updater)
    frozen: bool = False        # FrozenLayer semantics (transfer learning)
    weight_noise: Optional[Any] = None  # DropConnect/WeightNoise
    # (DL4J nn/conf/weightnoise/), applied to params in the train forward
    constraints: Tuple[Any, ...] = ()   # post-update projections
    # (DL4J nn/conf/constraint/), applied inside the compiled train step

    # ---- shape inference -------------------------------------------------
    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    # ---- params ----------------------------------------------------------
    def init(self, key, input_type: InputType, dtype=jnp.float32):
        """Returns (params, state); both possibly empty dicts."""
        return {}, {}

    def apply(self, params, state, x, *, train: bool = False, rng=None,
              mask=None):
        """Pure forward. Returns (output, new_state)."""
        raise NotImplementedError

    # ---- helpers ---------------------------------------------------------
    def maybe_dropout_input(self, x, train, rng):
        """DL4J applies layer `dropOut` to the layer *input* during training
        (Dropout in nn/conf/dropout applied via BaseLayer.applyDropOutIfNecessary).
        Accepts a float probability or an IDropout variant object."""
        if not train or rng is None:
            return x
        if isinstance(self.dropout, (int, float)):
            if self.dropout <= 0.0:
                return x
            keep = 1.0 - self.dropout
            mask = jax.random.bernoulli(rng, keep, x.shape)
            return jnp.where(mask, x / keep, 0.0)
        from deeplearning4j_tpu.nn.regularization import apply_input_dropout
        return apply_input_dropout(self.dropout, x, train, rng)

    def regularization_score(self, params) -> jnp.ndarray:
        """L1/L2 penalty contribution (DL4J BaseLayer.calcRegularizationScore).
        Applied to weight ("W"-like) params only, not biases, as in DL4J."""
        score = jnp.asarray(0.0, jnp.float32)
        if self.l1 == 0.0 and self.l2 == 0.0:
            return score
        for k, v in params.items():
            if k.startswith("b"):
                continue
            if self.l1:
                score = score + self.l1 * jnp.sum(jnp.abs(v))
            if self.l2:
                score = score + 0.5 * self.l2 * jnp.sum(v * v)
        return score

    def has_params(self) -> bool:
        return True


# ------------------------------------------------------------ preprocessors
def preprocess_forward(from_type: InputType, to_kind: Kind, x):
    """Reshape activations between layer kinds.

    The analog of DL4J InputPreProcessor implementations
    (CnnToFeedForwardPreProcessor, FeedForwardToRnnPreProcessor, ...,
    nn/conf/preprocessor/), auto-applied like
    MultiLayerConfiguration.Builder#setInputType does. Only the reshape-style
    preprocessors exist; layout is already TPU-native NHWC / (B,T,F)."""
    if from_type.kind == to_kind:
        return x
    b = x.shape[0]
    if to_kind == Kind.FF:
        return x.reshape(b, -1)   # CNN/RNN -> FF: flatten (RNN: requires T known)
    if from_type.kind == Kind.FF and to_kind == Kind.CNN:
        raise ValueError("FF->CNN preprocessing requires explicit target shape; "
                         "use a ReshapeVertex / specify InputType.convolutional")
    if from_type.kind == Kind.CNN and to_kind == Kind.RNN:
        # collapse spatial dims to time (DL4J CnnToRnnPreProcessor)
        h, w, c = from_type.shape
        return x.reshape(b, h * w, c)
    if from_type.kind == Kind.RNN and to_kind == Kind.CNN1D:
        return x
    if from_type.kind == Kind.CNN1D and to_kind == Kind.RNN:
        return x
    raise ValueError(f"No preprocessor from {from_type.kind} to {to_kind}")


def preprocessed_type(from_type: InputType, to_kind: Kind) -> InputType:
    if from_type.kind == to_kind:
        return from_type
    if to_kind == Kind.FF:
        return InputType(Kind.FF, (from_type.flat_size,))
    if from_type.kind == Kind.CNN and to_kind == Kind.RNN:
        h, w, c = from_type.shape
        return InputType(Kind.RNN, (h * w, c))
    if from_type.kind in (Kind.RNN, Kind.CNN1D) and to_kind in (Kind.RNN, Kind.CNN1D):
        return InputType(to_kind, from_type.shape)
    raise ValueError(f"No preprocessor from {from_type.kind} to {to_kind}")
