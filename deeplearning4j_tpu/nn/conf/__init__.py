from deeplearning4j_tpu.nn.conf.base import (
    InputType, LayerConf, register_layer, layer_from_dict, layer_to_dict,
)
from deeplearning4j_tpu.nn.conf.network import (
    MultiLayerConfiguration, NeuralNetConfiguration,
    ComputationGraphConfiguration, GraphBuilder,
)

__all__ = [
    "InputType", "LayerConf", "register_layer", "layer_from_dict",
    "layer_to_dict", "MultiLayerConfiguration", "NeuralNetConfiguration",
    "ComputationGraphConfiguration", "GraphBuilder",
]
