"""MultiLayerNetwork — the sequential network container.

Parity target: DL4J nn/multilayer/MultiLayerNetwork.java (3545 LoC):
- init()                    :549   -> init(): per-layer param init via InputType chain
- fit(DataSetIterator)      :1268  -> fit(): jit-compiled train step (autodiff
                                     replaces calcBackpropGradients :1378)
- feedForward               :885   -> feed_forward(): all layer activations
- output                    :2012  -> output(): jitted inference
- computeGradientAndScore   :2360  -> the value_and_grad inside the train step
- doTruncatedBPTT           :1315  -> tBPTT chunking with carried RNN state
- rnnTimeStep               :2806  -> rnn_time_step(): stateful streaming step
- score includes l1/l2 regularization (BaseLayer.calcRegularizationScore)

TPU-native design: the whole training step (forward, backward, updater apply)
is ONE jit-compiled XLA program with donated params/opt-state buffers (the
analog of DL4J's workspace arena reuse, MultiLayerNetwork.java:1284-1292).
Parameters are a pytree; the canonical flat view (util/params.py) replaces
DL4J's flattenedParams single buffer (:114,603-627).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.data.async_iterator import (
    AsyncDataSetIterator, host_cast,
)
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterator import ArrayDataSetIterator, DataSetIterator
from deeplearning4j_tpu.nn.conf.base import (
    InputType, Kind, LayerConf, preprocess_forward, preprocessed_type,
)
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.updaters import build_optimizer, NoOp
from deeplearning4j_tpu.util import params as param_util
from deeplearning4j_tpu.util.env import env_int
from deeplearning4j_tpu.util.platform import is_tpu_backend

log = logging.getLogger("deeplearning4j_tpu")

# layer-kind requirements for automatic preprocessor insertion
# (the analog of MultiLayerConfiguration.Builder#setInputType auto-adding
#  InputPreProcessors). None = accepts anything (elementwise layers).
_KIND_BY_CLASS = {
    "DenseLayer": Kind.FF, "EmbeddingLayer": Kind.FF, "OutputLayer": Kind.FF,
    "AutoEncoder": Kind.FF, "VariationalAutoencoder": Kind.FF,
    "ConvolutionLayer": Kind.CNN, "Deconvolution2D": Kind.CNN,
    "SeparableConvolution2D": Kind.CNN, "DepthwiseConvolution2D": Kind.CNN,
    "SubsamplingLayer": Kind.CNN, "Upsampling2D": Kind.CNN,
    "ZeroPaddingLayer": Kind.CNN, "Cropping2D": Kind.CNN,
    "SpaceToDepthLayer": Kind.CNN, "SpaceToBatchLayer": Kind.CNN,
    "Yolo2OutputLayer": Kind.CNN,
    "MultiHeadAttention": Kind.RNN, "TransformerBlock": Kind.RNN,
    "MoEFeedForward": Kind.RNN,
    "PositionalEmbeddingLayer": Kind.RNN, "EmbeddingSequenceLayer": Kind.RNN,
    "LocalResponseNormalization": Kind.CNN, "CnnLossLayer": Kind.CNN,
    "LSTM": Kind.RNN, "GravesLSTM": Kind.RNN, "SimpleRnn": Kind.RNN,
    "GRU": Kind.RNN,
    "Bidirectional": Kind.RNN, "GravesBidirectionalLSTM": Kind.RNN,
    "RnnOutputLayer": Kind.RNN, "RnnLossLayer": Kind.RNN,
    "LastTimeStep": Kind.RNN, "MaskZeroLayer": Kind.RNN,
    "Convolution1DLayer": Kind.RNN, "Subsampling1DLayer": Kind.RNN,
}

_RECURRENT_CLASSES = {"LSTM", "GravesLSTM", "SimpleRnn", "GRU"}


def _is_stateful_recurrent(layer) -> bool:
    """Recurrent-carry dispatch, unwrapping FrozenLayerWrapper so a
    frozen LSTM keeps its rnn_time_step/tbptt state semantics."""
    inner = getattr(layer, "layer", None)
    name = type(inner if inner is not None
                and type(layer).__name__ == "FrozenLayerWrapper"
                else layer).__name__
    return name in _RECURRENT_CLASSES


def _scan_incompatible_listeners(listeners) -> bool:
    """Listeners that inspect the model (params/opt state) or capture
    gradients need iteration_done in lockstep with the params — the
    pipelined scan fit delivers it up to 2K-1 steps late, so their
    presence forces the per-call path."""
    return any(getattr(lst, "wants_gradients", False)
               or getattr(lst, "reads_model", False)
               for lst in listeners)


def _record_iteration(score: float, batch_size: int,
                      step_seconds: Optional[float] = None,
                      sync_seconds: Optional[float] = None):
    """One optimizer step's worth of telemetry (monitor/metrics.py) —
    shared by every fit path of both containers and the resilient
    trainer, so `train_*` series mean the same thing everywhere. Only
    host scalars are touched: no device sync is introduced."""
    from deeplearning4j_tpu import monitor
    monitor.counter("train_iterations_total",
                    "Optimizer steps applied").inc()
    monitor.counter("train_examples_total",
                    "Training examples consumed").inc(batch_size)
    monitor.gauge("train_score", "Last training loss/score").set(score)
    if step_seconds is not None:
        monitor.histogram("train_step_seconds",
                          "Train step wall time (dispatch + host sync)"
                          ).observe(step_seconds)
    if sync_seconds is not None:
        monitor.histogram("train_host_sync_seconds",
                          "Blocking device->host loss fetch per step"
                          ).observe(sync_seconds)


def _run_scan_pipeline(batches, sig_of, dispatch, process, K, defer=True):
    """Shared chunking/deferral loop of the input-pipelined fit paths
    (MultiLayerNetwork._fit_epoch_scan/_fit_epoch_accum,
    ComputationGraph._fit_epoch_scan).

    Groups consecutive batches with identical shape signature `sig_of(b)`
    into chunks of at most K, calls `dispatch(group, etl_ms)` for each
    chunk (returning an opaque pending record whose device values are still
    futures), and calls `process(pending)` for chunk i only AFTER chunk
    i+1 has been dispatched — so the host-side stacking and dispatch of the
    next chunk overlaps the device compute of the current one, and the one
    blocking loss fetch per chunk happens while the device is busy.
    defer=False processes each chunk in lockstep instead (model-reading
    listeners must observe the params as of the step they're told about)."""
    from deeplearning4j_tpu import monitor
    pending = None
    group, gsig = [], None
    etl_start = time.perf_counter()

    def flush():
        nonlocal pending, group, etl_start
        etl_end = time.perf_counter()
        etl_ms = (etl_end - etl_start) * 1e3
        monitor.add_span("train/etl", etl_start, etl_end,
                         batches=len(group))
        monitor.counter("train_chunks_dispatched_total",
                        "Scan/accum chunks dispatched to the device").inc()
        with monitor.span("train/dispatch", batches=len(group)):
            fresh = dispatch(group, etl_ms)
        if not defer:
            with monitor.span("train/chunk_sync"):
                process(fresh)
        else:
            if pending is not None:
                with monitor.span("train/chunk_sync"):
                    process(pending)
            pending = fresh
        group, etl_start = [], time.perf_counter()

    for b in batches:
        s = sig_of(b)
        if group and (s != gsig or len(group) == K):
            flush()
        group.append(b)
        gsig = s
    if group:
        flush()
    if pending is not None:
        with monitor.span("train/chunk_sync"):
            process(pending)


def _required_kind(layer: LayerConf) -> Optional[Kind]:
    name = type(layer).__name__
    if name == "FrozenLayerWrapper":
        return _required_kind(layer.layer)
    return _KIND_BY_CLASS.get(name)


def _layer_call(layer, *, seq, train, remat, params, x, state=None,
                carry=None, rng=None, mask=None):
    """Invoke layer.apply (seq=False) or layer.apply_seq (seq=True), with
    jax.checkpoint rematerialization when remat is on: every traced value
    (params/state/carry/input/rng/mask) is a checkpoint ARGUMENT, only the
    static layer conf and train flag are closed over. Shared by both
    containers so the two forward passes can't drift."""
    if seq:
        def fn(lp, xx, cc, rr, mm, _l=layer):
            return _l.apply_seq(lp, xx, cc, train=train, rng=rr, mask=mm)
        args = (params, x, carry, rng, mask)
    else:
        def fn(lp, st, xx, rr, mm, _l=layer):
            return _l.apply(lp, st, xx, train=train, rng=rr, mask=mm)
        args = (params, state, x, rng, mask)
    if remat:
        fn = jax.checkpoint(fn)
    return fn(*args)


def _default_scan_steps() -> int:
    """Production fit() pipelining default, decided from the round-5
    hardware measurement (PERF.md): on the TPU v5e the scan-of-10 fused
    step measured +6.5% over per-call (2377 vs 2231 imgs/s, ResNet-50
    bf16 batch 128) and removes all per-step dispatch; on CPU XLA
    pessimizes convolutions inside scan (10.9x slower, PERF.md
    "mechanism check"), so per-call stays the CPU default.
    DL4J_TPU_SCAN_STEPS overrides either way."""
    env = env_int("DL4J_TPU_SCAN_STEPS")
    if env is not None:
        return env
    # TPU only — GPU/other backends are unmeasured, and the CPU
    # mechanism check shows conv-in-scan can regress badly off-TPU
    return 10 if is_tpu_backend() else 1


def _engage_plan_impl(net, plan):
    """Shared by MultiLayerNetwork/ComputationGraph (and the resilience
    drivers): activate a GSPMD ShardingPlan for a net's compiled steps —
    or plain single-device training when None. Either way
    params/opt/state are laundered into XLA-owned buffers
    (donated-buffer safety, util/params.owned_leaf); under a plan the
    laundered copies additionally land on the plan's placements
    (sharding-aware own_tree), and a plan CHANGE drops the compiled-step
    caches so the next step re-lowers against the new layout instead of
    silently running the old one."""
    prior = net._plan
    if plan != prior:
        net._plan = plan
        net._train_step = None
        net._scan_step = {}
        net._output_fn = None
        # the ledger cache keys on id(step_fn): with the old jitted fns
        # dropped above, CPython may reuse their ids for the NEW steps —
        # a stale hit would misattribute the re-compiled (sharded)
        # program's timings to the old record
        net._ledger_cache = {}
    if plan is None:
        if prior is not None:
            # leaving a plan: gather mesh-committed leaves back to the
            # default device FIRST — the owned copy below preserves
            # committed shardings, and a plain fit stages its batches
            # single-device (incompatible-devices error otherwise)
            dev = jax.local_devices()[0]
            gather = lambda t: jax.tree_util.tree_map(
                lambda a: jax.device_put(a, dev), t)
            net.params = gather(net.params)
            net.state = gather(net.state)
            net.opt_state = gather(net.opt_state)
        net.params = param_util.own_tree(net.params)
        net.state = param_util.own_tree(net.state)
        net.opt_state = param_util.own_tree(net.opt_state)
    else:
        net.params = param_util.own_tree(
            net.params, plan.param_shardings(net.params))
        net.state = param_util.own_tree(
            net.state, plan.replicated_shardings(net.state))
        net.opt_state = param_util.own_tree(
            net.opt_state, plan.opt_shardings(net.opt_state, net.params))


def _stage_with_affine(net, a):
    """Features -> device, shared by MultiLayerNetwork._stage_x and
    ComputationGraph._stage_x. With a device affine engaged (fit through
    a `device_affine()` pre-processor), RAW features ship over the
    host->HBM link (uint8 pixels stay uint8: 4x fewer bytes than
    float32, 2x fewer than the bf16 host cast) and the normalization
    runs on device in one fused jit; otherwise plain _as_jnp."""
    if net._input_affine is None:
        return _as_jnp(a, net._compute_dtype)
    if net._affine_fn is None:
        from deeplearning4j_tpu.data.normalization import make_affine_fn
        net._affine_fn = make_affine_fn(net._compute_dtype)
    shift, scale = net._input_affine
    return net._affine_fn(jnp.asarray(a), shift, scale)


def _as_jnp(a, dtype=None):
    if a is None:
        return None
    # 16-bit compute dtypes (bfloat16 training): cast float32 host arrays
    # BEFORE the device transfer (bit-identical to the device cast; f64 is
    # excluded — its old path double-rounds via f32 with x64 disabled).
    # Shared rule: data/async_iterator.host_cast (DL4J_TPU_HOST_CAST=0
    # restores transfer-then-cast).
    a = host_cast(a, dtype)
    arr = jnp.asarray(a)
    # floats cast to the compute dtype; so do raw uint8 image bytes
    # (ImageRecordReader reference parity) used WITHOUT a normalizer.
    # Wider int dtypes stay integer — they are embedding/sparse-label
    # token ids, not pixels.
    if dtype is not None and (jnp.issubdtype(arr.dtype, jnp.floating)
                              or arr.dtype == jnp.uint8):
        arr = arr.astype(dtype)
    return arr


def _masked_eval_pair(labels, preds, labels_mask):
    """Normalize (labels, preds) for the eval accumulators: drop
    mask-padded entries (mask reshaped to the labels' leading dims, so
    (B,T), (B,T,1) and (B,) layouts all work) and flatten remaining
    rank>=3 sequences to (N, C) so per-class accumulators see the class
    axis."""
    if labels_mask is not None:
        m = np.asarray(labels_mask).astype(bool).reshape(labels.shape[:-1])
        labels, preds = labels[m], preds[m]
    if labels.ndim >= 3:
        labels = labels.reshape(-1, labels.shape[-1])
        preds = preds.reshape(-1, preds.shape[-1])
    return labels, preds


def validate_layer_conf(layer: LayerConf):
    """Fail fast on unresolvable names at init time (typos in activation /
    weight_init / loss would otherwise only surface at first forward)."""
    from deeplearning4j_tpu.nn.activations import get_activation
    from deeplearning4j_tpu.nn.initializers import get_initializer
    from deeplearning4j_tpu.nn.losses import get_loss
    for field, resolver in (("activation", get_activation),
                            ("gate_activation", get_activation),
                            ("weight_init", get_initializer),
                            ("loss", get_loss)):
        v = getattr(layer, field, None)
        if v is not None:
            resolver(v)
    inner = getattr(layer, "layer", None)
    if isinstance(inner, LayerConf):
        validate_layer_conf(inner)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = conf.layers
        self.params: Optional[dict] = None
        self.state: Optional[dict] = None
        self.opt_state = None
        self.listeners: List = []
        self.iteration_count = 0
        self.epoch_count = 0
        self._score: Optional[float] = None
        self._rnn_carries: Dict[str, Any] = {}
        self._param_dtype = jnp.dtype(conf.dtype)
        self._compute_dtype = jnp.dtype(conf.compute_dtype or conf.dtype)
        self._input_types: Optional[List[InputType]] = None
        self._tx = None
        self._train_step = None
        self._scan_step: Dict[Any, Any] = {}
        self._output_fn = None
        self._input_affine = None   # (shift, scale) during device-norm fit
        self._affine_fn = None
        self._ledger_cache: Dict[Any, Any] = {}   # monitor.xla programs
        self._plan = None           # active GSPMD ShardingPlan (parallel/plan)

    # ------------------------------------------------------------ plumbing
    def _stage_x(self, a):
        return _stage_with_affine(self, a)

    def _engage_plan(self, plan):
        """Activate a GSPMD ShardingPlan (parallel/plan.py) for this
        net's compiled steps — or plain single-device training when
        None (the shared `_engage_plan_impl`; also used by
        ComputationGraph and the ResilientTrainer drivers)."""
        _engage_plan_impl(self, plan)

    def _shard_batch(self, *arrs, stacked: bool = False):
        """Place staged batch operands per the active plan — dim 0 (dim
        1 for host-stacked scan/accum chunks) split over the mesh "data"
        axis. Identity without a plan."""
        plan = self._plan
        if plan is None:
            return arrs
        return tuple(plan.shard_batch(a, stacked=stacked) for a in arrs)

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners):
        self.listeners.extend(listeners)
        return self

    def _resolve_types(self) -> List[InputType]:
        """Per-layer input InputTypes (pre-preprocessor), following DL4J's
        setInputType chain."""
        if self.conf.input_type is None:
            raise ValueError("MultiLayerConfiguration.input_type must be set "
                             "(InputType.feed_forward/convolutional/recurrent)")
        types = []
        cur = self.conf.input_type
        for layer in self.layers:
            need = _required_kind(layer)
            if need is not None and cur.kind != need:
                cur = preprocessed_type(cur, need)
            types.append(cur)
            cur = layer.output_type(cur)
        self._output_type = cur
        return types

    def init(self, seed: Optional[int] = None):
        """Initialize parameters and optimizer state (DL4J init(), :549)."""
        seed = self.conf.seed if seed is None else seed
        key = jax.random.PRNGKey(seed)
        for layer in self.layers:
            validate_layer_conf(layer)
        self._input_types = self._resolve_types()
        params: Dict[str, dict] = {}
        state: Dict[str, dict] = {}
        for i, layer in enumerate(self.layers):
            key, sub = jax.random.split(key)
            p, s = layer.init(sub, self._input_types[i], self._param_dtype)
            params[str(i)] = p
            state[str(i)] = s
        self.params = params
        self.state = state
        self._build_optimizer()
        return self

    def _label_params(self):
        """Per-layer updater labels for optax.multi_transform (per-layer
        updater overrides + FrozenLayer -> NoOp, DL4J UpdaterBlock grouping)."""
        labels = {}
        transforms = {"__global__": build_optimizer(
            self.conf.updater, self.conf.grad_clip_norm, self.conf.grad_clip_value)}
        any_override = False
        for i, layer in enumerate(self.layers):
            lab = "__global__"
            if layer.frozen or type(layer).__name__ == "FrozenLayerWrapper":
                lab = "__noop__"
                transforms.setdefault("__noop__", NoOp().to_optax())
                any_override = True
            elif layer.updater is not None:
                lab = f"layer_{i}"
                transforms[lab] = build_optimizer(
                    layer.updater, self.conf.grad_clip_norm, self.conf.grad_clip_value)
                any_override = True
            labels[str(i)] = jax.tree_util.tree_map(lambda _: lab, self.params[str(i)])
        return any_override, labels, transforms

    def _build_optimizer(self):
        any_override, labels, transforms = self._label_params()
        if any_override:
            self._tx = optax.multi_transform(transforms, labels)
        else:
            self._tx = transforms["__global__"]
        self.opt_state = self._tx.init(self.params)
        self._train_step = None     # force re-trace
        self._scan_step = {}

    # ------------------------------------------------------------- forward
    def _cast_params(self, params):
        if self._compute_dtype == self._param_dtype:
            return params
        def cast(a):
            if jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(self._compute_dtype)
            return a
        return jax.tree_util.tree_map(cast, params)

    def _forward(self, params, state, x, train, rng, fmask=None,
                 carries=None, collect=False, upto: Optional[int] = None):
        """Forward through layers [0, upto) with auto preprocessors
        (upto=None -> all layers).

        When `upto` cuts before the output head, the returned activation is
        additionally preprocessed into the head's required kind, ready for
        head.score(). Returns (activations list if collect else final
        activation, new_state, new_carries)."""
        if self._input_types is None:
            self._input_types = self._resolve_types()
        params = self._cast_params(params)
        x = _as_jnp(x, self._compute_dtype)
        cur_type = self.conf.input_type
        n = len(self.layers) if upto is None else upto
        new_state = dict(state)
        new_carries = {}
        acts = []
        for i, layer in enumerate(self.layers[:n]):
            need = _required_kind(layer)
            if need is not None and cur_type.kind != need:
                x = preprocess_forward(cur_type, need, x)
                cur_type = preprocessed_type(cur_type, need)
            sub_rng = None
            if rng is not None:
                rng, sub_rng = jax.random.split(rng)
            mask = fmask if cur_type.kind == Kind.RNN else None
            key = str(i)
            layer_params = params[key]
            if train and sub_rng is not None and layer.weight_noise is not None:
                from deeplearning4j_tpu.nn.regularization import (
                    apply_weight_noise,
                )
                sub_rng, noise_rng = jax.random.split(sub_rng)
                layer_params = apply_weight_noise(layer, layer_params, train,
                                                  noise_rng)
            # gradient checkpointing: rematerialize this layer's
            # activations in the backward pass instead of storing them —
            # HBM for recompute FLOPs (jax.checkpoint). Only the training
            # forward pays for a backward, so inference is untouched.
            remat = train and self.conf.gradient_checkpointing
            if carries is not None and _is_stateful_recurrent(layer):
                y, carry = _layer_call(
                    layer, seq=True, train=train, remat=remat,
                    params=layer_params, x=x, carry=carries.get(key),
                    rng=sub_rng, mask=mask)
                new_carries[key] = carry
                new_state[key] = state[key]
            else:
                y, s = _layer_call(
                    layer, seq=False, train=train, remat=remat,
                    params=layer_params, x=x, state=state[key],
                    rng=sub_rng, mask=mask)
                new_state[key] = s
            x = y
            cur_type = layer.output_type(cur_type)
            if collect:
                acts.append(x)
        if upto is not None and upto < len(self.layers):
            head = self.layers[upto]
            need = _required_kind(head)
            if need is not None and cur_type.kind != need:
                x = preprocess_forward(cur_type, need, x)
        return (acts if collect else x), new_state, new_carries

    def _score_fn(self, params, state, x, y, fmask, lmask, train, rng,
                  carries=None):
        """Loss on a batch: last-layer score + regularization
        (computeGradientAndScore, MultiLayerNetwork.java:2360)."""
        if not self.layers or not hasattr(self.layers[-1], "score"):
            raise ValueError("Last layer must be an output/loss layer with a "
                             "score() method to compute training loss")
        params_c = self._cast_params(params)
        # forward up to (but excluding) the output layer
        head = self.layers[-1]
        feat, new_state, new_carries = self._forward(
            params_c, state, x, train, rng, fmask, carries,
            upto=len(self.layers) - 1)
        out_mask = lmask if lmask is not None else (
            fmask if _required_kind(head) == Kind.RNN else None)
        loss = head.score(params_c[str(len(self.layers) - 1)], feat,
                          _as_jnp(y, self._compute_dtype), train=train,
                          rng=None, mask=out_mask)
        reg = jnp.asarray(0.0, jnp.float32)
        for i, layer in enumerate(self.layers):
            reg = reg + layer.regularization_score(params[str(i)])
        # score accumulates in f32 (bf16 compute) but must stay f64 under
        # float64 gradient checking — don't down-cast a wider loss
        score_dtype = jnp.promote_types(jnp.float32, loss.dtype)
        return loss.astype(score_dtype) + reg, (new_state, new_carries)

    # -------------------------------------------------------------- output
    def output(self, x, train: bool = False):
        """Inference (DL4J output(), :2012-2112). jit-compiled and cached."""
        if self.params is None:
            raise RuntimeError("Network is not initialized — call init() first")
        if self._output_fn is None:
            @jax.jit
            def _out(params, state, x):
                y, _, _ = self._forward(params, state, x, False, None)
                return y
            self._output_fn = _out
        return self._output_fn(self.params, self.state, _as_jnp(x, self._compute_dtype))

    def feed_forward(self, x, train: bool = False, rng=None):
        """All layer activations (DL4J feedForward(), :885-1071).
        With train=True and no rng given, a fresh dropout key is drawn per
        call (so repeated calls do not reuse one mask)."""
        if train and rng is None:
            self._ff_counter = getattr(self, "_ff_counter", 0) + 1
            rng = jax.random.fold_in(
                jax.random.PRNGKey(self.conf.seed + 15485863), self._ff_counter)
        acts, _, _ = self._forward(self.params, self.state, x, train,
                                   rng if train else None, collect=True)
        return acts

    # ----------------------------------------------------------------- fit
    def _make_train_step(self, with_fmask, with_lmask, with_carries,
                         with_stats=False):
        from deeplearning4j_tpu.nn.regularization import (
            apply_constraints, constraint_map, has_constraints,
        )
        tx = self._tx
        constrained = has_constraints(self.layers)
        layer_map = constraint_map(self)
        plan = self._plan   # GSPMD plan: sharding constraints in-jit

        def step(params, opt_state, state, x, y, fmask, lmask, rng, carries):
            def loss_fn(p):
                return self._score_fn(p, state, x, y, fmask, lmask, True, rng,
                                      carries=carries)
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if plan is not None:
                # pin grads to the ZeRO/TP compute layout: this single
                # hint makes XLA derive reduce-scatter -> sharded update
                # -> all-gather (parallel/plan.py)
                grads = plan.constrain_grads(grads)
            updates, new_opt = tx.update(grads, opt_state, params)
            if plan is not None:
                updates = plan.constrain_grads(updates)
            new_params = optax.apply_updates(params, updates)
            if constrained:     # post-update projection (DL4J applyConstraints)
                new_params = apply_constraints(layer_map, new_params)
            if plan is not None:
                new_params = plan.constrain_params(new_params)
                new_opt = plan.constrain_opt(new_opt, new_params)
                new_state = plan.constrain_replicated(new_state)
            if with_stats:
                # StatsListener capture iterations also return the raw
                # gradient and update pytrees (DL4J onGradientCalculation /
                # onBackwardPass hooks); a separate jit variant so the fast
                # path transfers nothing extra
                return (new_params, new_opt, new_state, loss, new_carries,
                        grads, updates)
            return new_params, new_opt, new_state, loss, new_carries

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _get_train_step(self, fmask, lmask, carries, with_stats=False):
        sig = (fmask is not None, lmask is not None, carries is not None,
               with_stats)
        if self._train_step is None:
            self._train_step = {}
        if sig not in self._train_step:
            self._train_step[sig] = self._make_train_step(*sig)
        return self._train_step[sig]

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            scan_steps: Optional[int] = None,
            prefetch: Optional[bool] = None,
            accumulate_steps: int = 1,
            plan=None):
        """Train (DL4J fit(DataSetIterator), :1268). Accepts a DataSetIterator,
        a DataSet, or (features, labels) arrays.

        accumulate_steps > 1: gradient accumulation — K micro-batch
        gradients averaged into ONE optimizer step inside one jit, for
        effective batch sizes beyond what HBM fits in a single forward
        (see _make_accum_step; mutually exclusive with scan_steps > 1,
        not applicable to tbptt). Accumulation groups only CONSECUTIVE
        same-shape micro-batches: a shape change (e.g. a non-drop-last
        partial tail) cuts the group short, and the short group takes one
        full-learning-rate step with the mean of however many gradients
        it holds — use drop_last/padded iterators for uniform shapes if K
        must be honored exactly (a warning fires once otherwise).

        scan_steps > 1 fuses that many optimizer steps into ONE jit call via
        lax.scan (input-pipelined fit): batches are stacked host-side while
        the previous chunk computes on device, and the per-step loss fetch is
        deferred one chunk, so the dispatch pipeline never blocks on a
        device→host sync. The RNG stream, update math and listener calls are
        identical to the per-call path (bit-for-bit, tested) — only the
        host/device overlap changes. Default: 10 on TPU (measured +6.5%
        over per-call, PERF.md), 1 on CPU; $DL4J_TPU_SCAN_STEPS overrides.

        Intended for dispatch-bound TPU loops. Caveat (PERF.md "mechanism
        check"): XLA:CPU pessimizes convolutions inside scan, so conv nets
        on CPU should keep scan_steps=1.

        `prefetch` (default on, kill switches DL4J_TPU_FIT_PREFETCH=0 /
        DL4J_TPU_PREFETCH_DEPTH=0): wrap plain sources in
        AsyncDataSetIterator, like the reference wraps every fit in an
        async iterator by default (MultiLayerNetwork.java:1272-1274) — a
        worker thread overlaps host ETL, the bf16 host cast, and the H2D
        transfer with device compute, DL4J_TPU_PREFETCH_DEPTH batches
        deep (default 2: double-buffered H2D). Already-async and
        async_supported=False sources pass through. Multi-process
        sources (data/pipeline.MultiProcessDataSetIterator, or the hot
        image path's automatic delegation in data/records.py) compose:
        the wrap's prefetch thread is the ring consumer, so worker
        decode, device DMA, and the compiled step all overlap — see
        docs/DATA_PIPELINE.md.

        `plan` (or an enclosing `parallel.use_mesh(plan)` context): a
        GSPMD ShardingPlan (parallel/plan.py) — the SAME compiled step
        runs SPMD over the plan's ("data", "model") mesh with DP
        all-reduce, tensor-parallel matmuls, and ZeRO reduce-scatter/
        all-gather as jit-inserted collectives. See docs/PARALLELISM.md."""
        if self.params is None:
            self.init()
        # donated-buffer safety: params from ANY host source (checkpoint,
        # keras/dl4j import, set_params_flat) may alias numpy memory that
        # the donating train step must not free (util/params.owned_leaf);
        # under a plan the laundered copies land on the plan placements
        from deeplearning4j_tpu.parallel.plan import active_plan
        if plan is None:
            plan = active_plan()
        self._engage_plan(plan)
        if accumulate_steps > 1:
            if self.conf.backprop_type == "tbptt":
                raise ValueError("accumulate_steps does not apply to "
                                 "tbptt (chunked-time) training")
            if scan_steps is not None and scan_steps > 1:
                raise ValueError("accumulate_steps and scan_steps are "
                                 "mutually exclusive (one fuses K "
                                 "optimizer steps, the other folds K "
                                 "micro-batches into one step)")
            scan_steps = 1
        if scan_steps is None:
            scan_steps = _default_scan_steps()
        iterator = self._as_iterator(data, batch_size)
        if prefetch is None:
            from deeplearning4j_tpu.data.async_iterator import (
                fit_prefetch_enabled,
            )
            prefetch = fit_prefetch_enabled()
        # device-side normalization (data/normalization.py
        # engaged_device_affine — env gate, listener gate, detach/restore,
        # feature-cast pause): an affine-representable pre-processor is
        # applied on device instead of host (_stage_x), so raw uint8
        # pixels ship over the link. Engaged BEFORE the async wrap so
        # the wrap skips the 16-bit FEATURE host cast — normalize-then-
        # cast preserves the f32 signal a premature bf16 cast would
        # quantize away (labels still ship 16-bit).
        from deeplearning4j_tpu.data.normalization import (
            engaged_device_affine)
        with engaged_device_affine(iterator, self.listeners) as aff:
            if aff is not None:
                self._input_affine = (jnp.asarray(aff[0]),
                                      jnp.asarray(aff[1]))
            # scan-fit and accumulation STACK K host batches before one
            # transfer — the wrap must not device_put per batch there (a
            # device array would round-trip back through the host). The
            # scan path falls back to per-call under model-reading
            # listeners and tbptt never scans, so match the path that
            # will actually run.
            stacking = accumulate_steps > 1 or (
                scan_steps > 1
                and self.conf.backprop_type != "tbptt"
                and not _scan_incompatible_listeners(self.listeners))
            copy_marked = []
            if stacking:
                # stacking holds K live batches before ONE transfer —
                # shared-memory ring iterators must yield copies for it
                # (their normal view batches are recycled on the next
                # pull; data/pipeline.mark_copy_for_stacking)
                from deeplearning4j_tpu.data.pipeline import (
                    mark_copy_for_stacking)
                copy_marked = mark_copy_for_stacking(iterator)
            if prefetch and not isinstance(iterator, AsyncDataSetIterator) \
                    and getattr(iterator, "async_supported", True):
                iterator = AsyncDataSetIterator(
                    iterator, device_put=not stacking,
                    # under a plan the worker thread stages straight onto
                    # the mesh (device arg accepts a Sharding), so the
                    # double-buffered H2D lands already batch-sharded
                    device=(self._plan.batch_sharding()
                            if self._plan is not None else None),
                    cast_dtype=self._compute_dtype
                    if np.dtype(self._compute_dtype).itemsize == 2
                    else None,
                    cast_features=self._input_affine is None)
            from deeplearning4j_tpu.monitor import goodput
            gp_session = goodput.fit_begin("mln/fit")
            try:
                from deeplearning4j_tpu import monitor
                for _ in range(epochs):
                    for lst in self.listeners:
                        lst.on_epoch_start(self, self.epoch_count)
                    with monitor.span("train/epoch",
                                      epoch=self.epoch_count):
                        if self.conf.backprop_type == "tbptt":
                            self._fit_epoch_tbptt(iterator)
                        elif accumulate_steps > 1:
                            self._fit_epoch_accum(iterator, accumulate_steps)
                        elif scan_steps > 1:
                            self._fit_epoch_scan(iterator, scan_steps)
                        else:
                            self._fit_epoch(iterator)
                    for lst in self.listeners:
                        lst.on_epoch_end(self, self.epoch_count)
                    self.epoch_count += 1
                    iterator.reset()
            finally:
                goodput.fit_end(gp_session)
                self._input_affine = None
                for it_ in copy_marked:
                    it_._copy = False
        return self

    def fit_pretrain(self, data, epochs: int = 1, batch_size: int = 32):
        """Greedy layerwise unsupervised pretraining (the `pretrain` branch
        of DL4J MultiLayerNetwork.fit, MultiLayerNetwork.java:1344-1346 over
        nn/layers/BasePretrainNetwork.java).

        For each layer exposing `pretrain_score` (AutoEncoder, VAE), in
        order: features are computed through the already-(pre)trained layers
        below in eval mode, and only that layer's params are optimized on
        its unsupervised objective. Supervised layers are skipped — follow
        with fit() to fine-tune end-to-end."""
        if self.params is None:
            self.init()
        iterator = self._as_iterator(data, batch_size)
        rng = jax.random.PRNGKey(self.conf.seed + 52711)
        for i, layer in enumerate(self.layers):
            if not hasattr(layer, "pretrain_score"):
                continue
            tx = build_optimizer(layer.updater or self.conf.updater,
                                 self.conf.grad_clip_norm,
                                 self.conf.grad_clip_value)
            lp = self.params[str(i)]
            opt_state = tx.init(lp)

            @jax.jit
            def feats_fn(params, state, x, _i=i):
                f, _, _ = self._forward(params, state, x, False, None,
                                        upto=_i)
                return f

            @jax.jit
            def pretrain_step(lp, opt_state, x, sub, _layer=layer, _tx=tx):
                loss, grads = jax.value_and_grad(
                    lambda p: _layer.pretrain_score(p, x, sub))(lp)
                updates, new_opt = _tx.update(grads, opt_state, lp)
                return optax.apply_updates(lp, updates), new_opt, loss

            for _ in range(epochs):
                for ds in iterator:
                    feats = feats_fn(self.params, self.state,
                                     _as_jnp(ds.features,
                                             self._compute_dtype))
                    rng, sub = jax.random.split(rng)
                    lp, opt_state, loss = pretrain_step(lp, opt_state,
                                                        feats, sub)
                iterator.reset()
            self.params[str(i)] = lp
            self._score = float(loss)
            log.info("pretrained layer %d (%s): score %.5f", i,
                     type(layer).__name__, self._score)
        self._build_optimizer()     # fresh opt state for supervised fit()
        return self

    def _as_iterator(self, data, batch_size) -> DataSetIterator:
        if isinstance(data, DataSetIterator):
            return data
        if isinstance(data, DataSet):
            from deeplearning4j_tpu.data.iterator import ExistingDataSetIterator
            return ExistingDataSetIterator([data])
        if isinstance(data, (tuple, list)) and len(data) == 2:
            return ArrayDataSetIterator(data[0], data[1], batch_size=batch_size)
        raise ValueError(f"Cannot interpret training data: {type(data)}")

    def _fit_epoch(self, iterator):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor import goodput
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        etl_start = time.perf_counter()
        rng = jax.random.PRNGKey(self.conf.seed + 7919 * (self.epoch_count + 1))
        grad_listeners = [lst for lst in self.listeners
                          if getattr(lst, "wants_gradients", False)]
        for ds in iterator:
            step_start = time.perf_counter()
            etl_ms = (step_start - etl_start) * 1e3
            monitor.add_span("train/etl", etl_start, step_start,
                             iteration=self.iteration_count)
            rng, sub = jax.random.split(rng)
            capture = [lst for lst in grad_listeners
                       if lst.should_capture(self.iteration_count)]
            step = self._get_train_step(ds.features_mask, ds.labels_mask,
                                        None, with_stats=bool(capture))
            xs = self._stage_x(ds.features)
            ys = _as_jnp(ds.labels, self._compute_dtype)
            fm = _as_jnp(ds.features_mask)
            lm = _as_jnp(ds.labels_mask)
            xs, ys, fm, lm = self._shard_batch(xs, ys, fm, lm)
            out = step(self.params, self.opt_state, self.state,
                       xs, ys, fm, lm, sub, None)
            grads = updates = None
            if capture:
                (self.params, self.opt_state, self.state, loss, _,
                 grads, updates) = out
            else:
                self.params, self.opt_state, self.state, loss, _ = out
            sync_start = time.perf_counter()
            # block for device completion FIRST (goodput: step_compute;
            # banks per-shard barrier wait under a plan), so the
            # host_sync span below covers only the narrow D2H fetch
            goodput.device_wait(loss)
            fetch_start = time.perf_counter()
            monitor.add_span("train/device_wait", sync_start, fetch_start)
            # graftlint: disable=host-sync-in-hot-path -- the step's ONE budgeted loss fetch (the deliberate per-iteration sync; PERF.md) — bracketed by the train/host_sync span
            self._score = float(loss)     # the step's one blocking fetch
            step_end = time.perf_counter()
            bs = int(np.shape(ds.features)[0])
            monitor.add_span("train/host_sync", fetch_start, step_end)
            monitor.add_span("train/step", step_start, step_end,
                             iteration=self.iteration_count,
                             score=self._score, batch_size=bs)
            if xla_ledger.enabled():
                key = (id(step), xla_ledger.shape_key((xs, ys, fm, lm)))
                fresh = key not in self._ledger_cache
                rec = xla_ledger.capture_cached(
                    self._ledger_cache, key, "mln/train_step", step,
                    (self.params, self.opt_state, self.state, xs, ys, fm,
                     lm, sub, None), examples_per_call=bs)
                if not fresh:
                    # the debut execution's wall time includes the jit
                    # compile — only steady-state steps feed the MFU gauge
                    xla_ledger.observe_step(rec, step_end - step_start)
            _record_iteration(self._score, bs,
                              step_seconds=step_end - step_start,
                              sync_seconds=step_end - fetch_start)
            for lst in capture:
                lst.on_gradients(self, self.iteration_count, self.epoch_count,
                                 grads, updates)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count,
                                   self.epoch_count, self._score, etl_ms, bs)
            self.iteration_count += 1
            etl_start = time.perf_counter()

    def _make_scan_step(self, with_fmask, with_lmask, K):
        """K optimizer steps fused into one jit via lax.scan. Same math as
        _make_train_step applied K times; returns the K per-step losses as a
        device array so the host never syncs inside the chunk."""
        from deeplearning4j_tpu.nn.regularization import (
            apply_constraints, constraint_map, has_constraints,
        )
        tx = self._tx
        constrained = has_constraints(self.layers)
        layer_map = constraint_map(self)
        plan = self._plan   # GSPMD plan: sharding constraints in-jit

        def kstep(params, opt_state, state, xs, ys, fms, lms, subs):
            def body(carry, batch):
                params, opt_state, state = carry
                x, y, fm, lm, sub = batch
                def loss_fn(p):
                    return self._score_fn(p, state, x, y, fm, lm, True, sub,
                                          carries=None)
                (loss, (new_state, _)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                if plan is not None:
                    grads = plan.constrain_grads(grads)
                updates, new_opt = tx.update(grads, opt_state, params)
                if plan is not None:
                    updates = plan.constrain_grads(updates)
                new_params = optax.apply_updates(params, updates)
                if constrained:
                    new_params = apply_constraints(layer_map, new_params)
                if plan is not None:
                    new_params = plan.constrain_params(new_params)
                    new_opt = plan.constrain_opt(new_opt, new_params)
                    new_state = plan.constrain_replicated(new_state)
                return (new_params, new_opt, new_state), loss

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state), (xs, ys, fms, lms, subs))
            return params, opt_state, state, losses

        return jax.jit(kstep, donate_argnums=(0, 1, 2))

    def _make_accum_step(self, with_stats):
        """Gradient accumulation: K micro-batch gradients averaged into
        ONE optimizer step, all inside one jit (TPU-native big-effective-
        batch training — the HBM cost is one extra gradient-sized
        accumulator, not a K-times batch). For equal micro-batch sizes
        and batch-independent layers the result is bit-comparable to one
        big-batch step (mean of equal-size micro means == full-batch
        mean; tested); BatchNormalization statistics remain per
        micro-batch, the same semantics every framework's accumulation
        has. with_stats additionally returns the averaged (grads,
        updates) for on_gradients listeners. One jit serves every
        chunk/mask shape (jax retraces per pytree structure)."""
        from deeplearning4j_tpu.nn.regularization import (
            apply_constraints, constraint_map, has_constraints,
        )
        tx = self._tx
        constrained = has_constraints(self.layers)
        layer_map = constraint_map(self)
        plan = self._plan   # GSPMD plan: sharding constraints in-jit

        def kaccum(params, opt_state, state, xs, ys, fms, lms, subs):
            def body(carry, batch):
                gsum, state = carry
                x, y, fm, lm, sub = batch
                def loss_fn(p):
                    return self._score_fn(p, state, x, y, fm, lm, True,
                                          sub, carries=None)
                (loss, (new_state, _)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
                if plan is not None:
                    # the accumulator carries in the ZeRO layout: micro-
                    # batch grads reduce-scatter into it instead of ever
                    # materializing whole per chip
                    gsum = plan.constrain_grads(gsum)
                return (gsum, new_state), loss

            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
            (gsum, state), losses = jax.lax.scan(
                body, (zeros, state), (xs, ys, fms, lms, subs))
            grads = jax.tree_util.tree_map(
                lambda g: g / subs.shape[0], gsum)
            updates, new_opt = tx.update(grads, opt_state, params)
            if plan is not None:
                updates = plan.constrain_grads(updates)
            new_params = optax.apply_updates(params, updates)
            if constrained:
                new_params = apply_constraints(layer_map, new_params)
            if plan is not None:
                new_params = plan.constrain_params(new_params)
                new_opt = plan.constrain_opt(new_opt, new_params)
                state = plan.constrain_replicated(state)
            if with_stats:
                return (new_params, new_opt, state, jnp.mean(losses),
                        grads, updates)
            return new_params, new_opt, state, jnp.mean(losses)

        return jax.jit(kaccum, donate_argnums=(0, 1, 2))

    def _get_accum_step(self, with_stats=False):
        sig = ("accum", with_stats)
        if sig not in self._scan_step:
            self._scan_step[sig] = self._make_accum_step(with_stats)
        return self._scan_step[sig]

    def _fit_epoch_accum(self, iterator, K):
        """One optimizer step per K micro-batches (gradient accumulation).
        Iteration counting follows DL4J's meaning (one iteration = one
        optimizer step); a ragged tail (< K same-shape batches) still
        accumulates into one step with the correct 1/len mean. Gradient
        listeners receive the AVERAGED per-step grads/updates (lockstep
        — wants_gradients forces defer=False below, so iteration_count
        at dispatch is the step being reported)."""
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        rng = jax.random.PRNGKey(self.conf.seed
                                 + 7919 * (self.epoch_count + 1))
        grad_listeners = [lst for lst in self.listeners
                          if getattr(lst, "wants_gradients", False)]
        sigs_seen = set()
        warned_partial = [False]
        last_sync = [None]

        def process(p):
            loss, bs, etl_ms, capture, grads, updates, rec = p
            self._score = float(loss)
            if xla_ledger.enabled():
                now = time.perf_counter()
                if rec is not None and last_sync[0] is not None:
                    xla_ledger.observe_step(rec, now - last_sync[0])
                last_sync[0] = now
            _record_iteration(self._score, bs)
            for lst in capture:
                lst.on_gradients(self, self.iteration_count,
                                 self.epoch_count, grads, updates)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count,
                                   self.epoch_count, self._score, etl_ms,
                                   bs)
            self.iteration_count += 1

        def dispatch(group, etl_ms):
            nonlocal rng
            if len(group) < K and not warned_partial[0]:
                # _run_scan_pipeline only groups CONSECUTIVE same-shape
                # batches: a shape change (e.g. a non-drop-last partial
                # tail) cuts the accumulation group short, and the short
                # group still takes ONE full-learning-rate optimizer step
                # with the mean of len(group) gradients — K is silently
                # not honored for it. Surface that once.
                warned_partial[0] = True
                cause = ("the micro-batch shape changed mid-epoch (use "
                         "drop_last or padded iterators for uniform "
                         "shapes)" if len(sigs_seen) > 1
                         else "the epoch ended mid-group")
                log.warning(
                    "fit(accumulate_steps=%d): dispatching an accumulation "
                    "group of only %d micro-batch(es) because %s; the "
                    "partial group takes one full-learning-rate step with "
                    "the 1/%d gradient mean", K, len(group), cause,
                    len(group))
            subs = []
            for _ in group:
                rng, sub = jax.random.split(rng)
                subs.append(sub)
            ds0 = group[0]
            stack = lambda get, dt=None: (
                None if get(ds0) is None else
                _as_jnp(np.stack([np.asarray(get(d)) for d in group]), dt))
            xs = None if ds0.features is None else self._stage_x(
                np.stack([np.asarray(d.features) for d in group]))
            ys = stack(lambda d: d.labels, self._compute_dtype)
            fms = stack(lambda d: d.features_mask)
            lms = stack(lambda d: d.labels_mask)
            xs, ys, fms, lms = self._shard_batch(xs, ys, fms, lms,
                                                 stacked=True)
            capture = [lst for lst in grad_listeners
                       if lst.should_capture(self.iteration_count)]
            kstep = self._get_accum_step(with_stats=bool(capture))
            subs_d = jnp.stack(subs)
            out = kstep(self.params, self.opt_state, self.state, xs, ys,
                        fms, lms, subs_d)
            grads = updates = None
            if capture:
                (self.params, self.opt_state, self.state, loss, grads,
                 updates) = out
            else:
                self.params, self.opt_state, self.state, loss = out
            bs = int(np.shape(ds0.features)[0]) * len(group)
            rec = None
            if xla_ledger.enabled():
                key = (id(kstep), xla_ledger.shape_key((xs, ys, fms, lms)))
                fresh = key not in self._ledger_cache
                rec = xla_ledger.capture_cached(
                    self._ledger_cache, key,
                    "mln/accum_step", kstep,
                    (self.params, self.opt_state, self.state, xs, ys, fms,
                     lms, subs_d), examples_per_call=bs,
                    steps_per_call=len(group))
                if fresh:
                    last_sync[0] = None   # exclude the AOT compile interval
            return loss, bs, etl_ms, capture, grads, updates, rec

        def sig_of(ds):
            s = (np.shape(ds.features), np.shape(ds.labels),
                 None if ds.features_mask is None
                 else np.shape(ds.features_mask),
                 None if ds.labels_mask is None
                 else np.shape(ds.labels_mask))
            sigs_seen.add(s)
            return s

        # unlike scan-fit, accumulation cannot fall back to per-call for
        # model-reading listeners (that would change the optimization) —
        # it drops the one-chunk deferral instead so each callback sees
        # the params of the step it reports
        _run_scan_pipeline(iterator, sig_of, dispatch, process, K,
                           defer=not _scan_incompatible_listeners(
                               self.listeners))

    def _get_scan_step(self, fmask, lmask, K):
        sig = (fmask is not None, lmask is not None, K)
        if sig not in self._scan_step:
            self._scan_step[sig] = self._make_scan_step(*sig)
        return self._scan_step[sig]

    def _fit_epoch_scan(self, iterator, K):
        """Input-pipelined epoch: group consecutive same-shape batches into
        chunks of K, stack host-side, run one scan-of-K jit per chunk, and
        defer the loss fetch by one chunk so stacking/dispatch of chunk i+1
        overlaps chunk i's device compute. Ragged tails (or a shape change
        mid-epoch) fall back to per-call steps for those batches."""
        if _scan_incompatible_listeners(self.listeners):
            return self._fit_epoch(iterator)
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        rng = jax.random.PRNGKey(self.conf.seed + 7919 * (self.epoch_count + 1))
        last_sync = [None]   # previous chunk-sync stamp: chunk wall clock

        def process(p):
            losses, bs, etl_ms, rec = p
            arr = np.asarray(losses)            # single blocking fetch/chunk
            if xla_ledger.enabled():
                # steady-state chunk wall time = spacing between chunk
                # syncs (the pipelined path has no un-overlapped "this
                # chunk only" interval to time; the first chunk is
                # skipped). The stamp advances on EVERY chunk — a ragged
                # tail (rec None) must not leak its wall time into the
                # next scan chunk's interval.
                now = time.perf_counter()
                if rec is not None and last_sync[0] is not None:
                    xla_ledger.observe_step(rec, now - last_sync[0])
                last_sync[0] = now
            for loss in arr:
                # graftlint: disable=host-sync-in-hot-path -- chunk losses are already host-resident (np.asarray above IS the deferred chunk sync); this is per-iteration bookkeeping
                self._score = float(loss)
                _record_iteration(self._score, bs)
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count,
                                       self.epoch_count, self._score,
                                       etl_ms, bs)
                self.iteration_count += 1
                etl_ms = 0.0

        def dispatch(group, etl_ms):
            nonlocal rng
            subs = []
            for _ in group:
                rng, sub = jax.random.split(rng)
                subs.append(sub)
            ds0 = group[0]
            rec = None
            if len(group) < K:
                # ragged tail / shape-change remainder: reuse the already
                # compiled per-call step rather than compiling a one-off
                # scan-of-len(group) program
                step = self._get_train_step(ds0.features_mask,
                                            ds0.labels_mask, None)
                losses = []
                for ds, sub in zip(group, subs):
                    txs, tys, tfm, tlm = self._shard_batch(
                        self._stage_x(ds.features),
                        _as_jnp(ds.labels, self._compute_dtype),
                        _as_jnp(ds.features_mask),
                        _as_jnp(ds.labels_mask))
                    out = step(self.params, self.opt_state, self.state,
                               txs, tys, tfm, tlm, sub, None)
                    self.params, self.opt_state, self.state, loss, _ = out
                    losses.append(loss)
                losses = jnp.stack(losses)
            else:
                stack = lambda get, dt=None: (
                    None if get(ds0) is None else
                    _as_jnp(np.stack([np.asarray(get(d)) for d in group]),
                            dt))
                xs = None if ds0.features is None else self._stage_x(
                    np.stack([np.asarray(d.features) for d in group]))
                ys = stack(lambda d: d.labels, self._compute_dtype)
                fms = stack(lambda d: d.features_mask)
                lms = stack(lambda d: d.labels_mask)
                xs, ys, fms, lms = self._shard_batch(xs, ys, fms, lms,
                                                     stacked=True)
                kstep = self._get_scan_step(fms, lms, len(group))
                subs_d = jnp.stack(subs)
                (self.params, self.opt_state, self.state,
                 losses) = kstep(self.params, self.opt_state, self.state,
                                 xs, ys, fms, lms, subs_d)
                if xla_ledger.enabled():
                    key = (id(kstep),
                           xla_ledger.shape_key((xs, ys, fms, lms)))
                    fresh = key not in self._ledger_cache
                    rec = xla_ledger.capture_cached(
                        self._ledger_cache, key,
                        "mln/scan_step", kstep,
                        (self.params, self.opt_state, self.state, xs, ys,
                         fms, lms, subs_d),
                        examples_per_call=(
                            int(np.shape(ds0.features)[0]) * len(group)),
                        steps_per_call=len(group))
                    if fresh:
                        # the capture's AOT compile sat inside this
                        # inter-chunk interval — restart the MFU clock so
                        # it can't read as a slow chunk
                        last_sync[0] = None
            return losses, int(np.shape(ds0.features)[0]), etl_ms, rec

        def sig_of(ds):
            return (np.shape(ds.features), np.shape(ds.labels),
                    None if ds.features_mask is None
                    else np.shape(ds.features_mask),
                    None if ds.labels_mask is None
                    else np.shape(ds.labels_mask))

        _run_scan_pipeline(iterator, sig_of, dispatch, process, K)

    def _fit_epoch_tbptt(self, iterator):
        """Truncated BPTT: chunk the time axis, carry RNN state across chunks,
        stop gradients at chunk boundaries (doTruncatedBPTT, :1315-1317)."""
        fwd = self.conf.tbptt_fwd_length
        rng = jax.random.PRNGKey(self.conf.seed + 104729 * (self.epoch_count + 1))
        for ds in iterator:
            T = ds.features.shape[1]
            carries = {}
            for t0 in range(0, T, fwd):
                t1 = min(t0 + fwd, T)
                x = ds.features[:, t0:t1]
                y = ds.labels[:, t0:t1] if ds.labels is not None and ds.labels.ndim >= 3 else ds.labels
                fm = ds.features_mask[:, t0:t1] if ds.features_mask is not None else None
                lm = ds.labels_mask[:, t0:t1] if ds.labels_mask is not None else None
                rng, sub = jax.random.split(rng)
                step = self._get_train_step(fm, lm, carries)
                txs, tys, tfm, tlm = self._shard_batch(
                    self._stage_x(x), _as_jnp(y, self._compute_dtype),
                    _as_jnp(fm), _as_jnp(lm))
                self.params, self.opt_state, self.state, loss, new_carries = step(
                    self.params, self.opt_state, self.state,
                    txs, tys, tfm, tlm, sub, carries)
                # stop gradient across chunk boundary
                carries = jax.tree_util.tree_map(jax.lax.stop_gradient, new_carries)
                # graftlint: disable=host-sync-in-hot-path -- the tbptt chunk's one budgeted loss fetch
                self._score = float(loss)
                _record_iteration(self._score, int(np.shape(x)[0]))
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count,
                                       self.epoch_count, self._score, 0.0,
                                       int(np.shape(x)[0]))
                self.iteration_count += 1

    # ------------------------------------------------------------- scoring
    def score(self, dataset: Optional[DataSet] = None) -> float:
        """Last training score, or score on a given DataSet (DL4J score())."""
        if dataset is None:
            return self._score if self._score is not None else float("nan")
        loss, _ = self._score_fn(self.params, self.state,
                                 _as_jnp(dataset.features, self._compute_dtype),
                                 _as_jnp(dataset.labels, self._compute_dtype),
                                 _as_jnp(dataset.features_mask),
                                 _as_jnp(dataset.labels_mask), False, None)
        return float(loss)

    def evaluate(self, data, batch_size: int = 32):
        """Classification evaluation (DL4J evaluate(DataSetIterator))."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        return self._evaluate_with(Evaluation(), data, batch_size)

    def evaluate_roc(self, data, batch_size: int = 32):
        """Binary ROC evaluation (DL4J evaluateROC(DataSetIterator))."""
        from deeplearning4j_tpu.eval.roc import ROC
        return self._evaluate_with(ROC(), data, batch_size)

    def evaluate_roc_multi_class(self, data, batch_size: int = 32):
        """One-vs-all per-class ROC (DL4J evaluateROCMultiClass)."""
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        return self._evaluate_with(ROCMultiClass(), data, batch_size)

    def _evaluate_with(self, ev, data, batch_size: int = 32):
        iterator = self._as_iterator(data, batch_size)
        for ds in iterator:
            ev.eval(*_masked_eval_pair(
                np.asarray(ds.labels), np.asarray(self.output(ds.features)),
                ds.labels_mask))
        iterator.reset()
        return ev

    def evaluate_regression(self, data, batch_size: int = 32):
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        ev = self._evaluate_with(RegressionEvaluation(), data, batch_size)
        return ev

    # ----------------------------------------------------- recurrent state
    def rnn_time_step(self, x):
        """Stateful single/multi-step streaming inference
        (DL4J rnnTimeStep, MultiLayerNetwork.java:2806). x: (B, F) one step or
        (B, T, F) several steps; recurrent layer state persists across calls."""
        x = _as_jnp(x, self._compute_dtype)
        single = x.ndim == 2
        if single:
            x = x[:, None, :]
        y, _, new_carries = self._forward(self.params, self.state, x, False,
                                          None, carries=self._rnn_carries)
        self._rnn_carries = new_carries
        return y[:, -1, :] if single and y.ndim == 3 else y

    def rnn_clear_previous_state(self):
        self._rnn_carries = {}

    # ------------------------------------------------------------ summary
    def summary(self) -> str:
        """Layer table: name, type, shapes, parameter count
        (MultiLayerNetwork.summary(), MultiLayerNetwork.java:3230)."""
        if self.params is None:
            raise RuntimeError("init() the network before summary()")
        types = self._input_types or self._resolve_types()
        rows = [("idx", "type", "in", "out", "params")]
        total = 0
        for i, layer in enumerate(self.layers):
            in_t = types[i]
            out_t = layer.output_type(in_t)
            n = param_util.num_params(self.params[str(i)])
            total += n
            rows.append((str(i), type(layer).__name__,
                         "x".join(map(str, in_t.shape)),
                         "x".join(map(str, out_t.shape)), f"{n:,}"))
        return param_util.format_param_table(rows, total)

    # ------------------------------------------------------------ memory
    def memory_report(self, batch_size: int = 32, with_compiled: bool = True):
        """Per-layer analytic memory estimate + exact XLA compiled-step HBM
        (DL4J LayerMemoryReport/NetworkMemoryReport analog, exceeded via
        jit(...).compile().memory_analysis())."""
        from deeplearning4j_tpu.util.memory import build_memory_report
        return build_memory_report(self, batch_size, with_compiled)

    # ------------------------------------------------------------ params
    def num_params(self) -> int:
        return param_util.num_params(self.params)

    def params_flat(self):
        """Canonical flat parameter vector (DL4J's flattenedParams view)."""
        return param_util.params_to_flat(self.params)

    def set_params_flat(self, flat):
        self.params = param_util.flat_to_params(flat, self.params)

    def copy(self) -> "MultiLayerNetwork":
        clone = MultiLayerNetwork(self.conf)
        if self.params is not None:
            clone._input_types = self._resolve_types()
            # materialize NEW buffers: the original's arrays are donated by
            # its train step and would be deleted out from under the clone
            clone.params = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True), self.params)
            clone.state = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True), self.state)
            clone._build_optimizer()
        return clone


