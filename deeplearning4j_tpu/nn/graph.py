"""ComputationGraph — the DAG network container.

Parity target: DL4J nn/graph/ComputationGraph.java (3904 LoC):
- topological order        :152,401 -> ComputationGraphConfiguration.topological_order()
- fit(MultiDataSetIterator):1015    -> fit(): jitted train step over the DAG
- feedForward              :1409    -> feed_forward(): dict of all activations
- output                   :1759    -> output()
- multi-input / multi-output with per-output losses summed into one score

The DAG executes inside ONE jit trace — XLA sees the whole graph and fuses
across vertices (DL4J walks GraphVertex objects at runtime instead).
"""
from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn.conf.base import (
    InputType, Kind, LayerConf, preprocess_forward, preprocessed_type,
)
from deeplearning4j_tpu.nn.conf.graph_vertices import GraphVertexConf
from deeplearning4j_tpu.nn.conf.network import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.multilayer import (
    _as_jnp, _default_scan_steps, _record_iteration, _required_kind,
    _run_scan_pipeline, _scan_incompatible_listeners,
)
from deeplearning4j_tpu.nn.updaters import NoOp, build_optimizer
from deeplearning4j_tpu.util import params as param_util

log = logging.getLogger("deeplearning4j_tpu")


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: Optional[dict] = None
        self.state: Optional[dict] = None
        self.opt_state = None
        self.listeners: List = []
        self.iteration_count = 0
        self.epoch_count = 0
        self._score: Optional[float] = None
        self._param_dtype = jnp.dtype(conf.dtype)
        self._compute_dtype = jnp.dtype(conf.compute_dtype or conf.dtype)
        self._topo = conf.topological_order()
        self._vertex_types: Optional[Dict[str, InputType]] = None
        self._tx = None
        self._train_step = None
        self._scan_step: Dict[Any, Any] = {}
        self._output_fn = None
        self._input_affine = None   # (shift, scale) during device-norm fit
        self._affine_fn = None
        self._ledger_cache: Dict[Any, Any] = {}   # monitor.xla programs
        self._plan = None           # active GSPMD ShardingPlan (parallel/plan)

    def _engage_plan(self, plan):
        """Activate a GSPMD ShardingPlan for this graph's compiled steps
        (the shared MultiLayerNetwork._engage_plan_impl contract)."""
        from deeplearning4j_tpu.nn.multilayer import _engage_plan_impl
        _engage_plan_impl(self, plan)

    def _shard_tuple(self, t, stacked: bool = False):
        """Place one tuple of staged batch operands (graph inputs/labels/
        masks) per the active plan; identity without one."""
        plan = self._plan
        if plan is None or t is None:
            return t
        return tuple(None if a is None else plan.shard_batch(a, stacked=stacked)
                     for a in t)

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    def _stage_x(self, a):
        from deeplearning4j_tpu.nn.multilayer import _stage_with_affine
        return _stage_with_affine(self, a)

    # ----------------------------------------------------------- init/types
    def _resolve_types(self) -> Dict[str, InputType]:
        """InputType for every vertex output (DL4J getLayerActivationTypes)."""
        if len(self.conf.input_types) != len(self.conf.network_inputs):
            raise ValueError("ComputationGraphConfiguration.input_types must "
                             "match network_inputs")
        types: Dict[str, InputType] = dict(zip(self.conf.network_inputs,
                                               self.conf.input_types))
        self._pre_kind: Dict[str, Optional[Kind]] = {}
        for name in self._topo:
            vd = self.conf.vertices[name]
            in_types = [types[i] for i in vd.inputs]
            if isinstance(vd.vertex, GraphVertexConf):
                self._pre_kind[name] = None
                types[name] = vd.vertex.output_type(*in_types)
            else:
                need = _required_kind(vd.vertex)
                self._pre_kind[name] = need
                t = in_types[0]
                if need is not None and t.kind != need:
                    t = preprocessed_type(t, need)
                types[name] = vd.vertex.output_type(t)
        return types

    def init(self, seed: Optional[int] = None):
        seed = self.conf.seed if seed is None else seed
        key = jax.random.PRNGKey(seed)
        for name in self.conf.network_outputs:
            if name not in self.conf.vertices:
                raise ValueError(f"Unknown output vertex '{name}'")
            v = self.conf.vertices[name].vertex
            if not hasattr(v, "score"):
                raise ValueError(
                    f"Output vertex '{name}' ({type(v).__name__}) must be an "
                    "output/loss layer with a score() method")
        from deeplearning4j_tpu.nn.multilayer import validate_layer_conf
        for vd in self.conf.vertices.values():
            if isinstance(vd.vertex, LayerConf):
                validate_layer_conf(vd.vertex)
        self._vertex_types = self._resolve_types()
        params: Dict[str, dict] = {}
        state: Dict[str, dict] = {}
        for name in self._topo:
            vd = self.conf.vertices[name]
            if isinstance(vd.vertex, GraphVertexConf):
                continue
            key, sub = jax.random.split(key)
            in_t = self._vertex_types[vd.inputs[0]]
            need = self._pre_kind[name]
            if need is not None and in_t.kind != need:
                in_t = preprocessed_type(in_t, need)
            p, s = vd.vertex.init(sub, in_t, self._param_dtype)
            params[name] = p
            state[name] = s
        self.params = params
        self.state = state
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        transforms = {"__global__": build_optimizer(
            self.conf.updater, self.conf.grad_clip_norm, self.conf.grad_clip_value)}
        labels = {}
        any_override = False
        for name, p in self.params.items():
            vd = self.conf.vertices[name]
            lab = "__global__"
            if getattr(vd.vertex, "frozen", False) or \
                    type(vd.vertex).__name__ == "FrozenLayerWrapper":
                lab = "__noop__"
                transforms.setdefault("__noop__", NoOp().to_optax())
                any_override = True
            elif getattr(vd.vertex, "updater", None) is not None:
                lab = f"v_{name}"
                transforms[lab] = build_optimizer(
                    vd.vertex.updater, self.conf.grad_clip_norm,
                    self.conf.grad_clip_value)
                any_override = True
            labels[name] = jax.tree_util.tree_map(lambda _: lab, p)
        if any_override:
            self._tx = optax.multi_transform(transforms, labels)
        else:
            self._tx = transforms["__global__"]
        self.opt_state = self._tx.init(self.params)
        self._train_step = None
        self._scan_step = {}

    # -------------------------------------------------------------- forward
    def _cast_params(self, params):
        if self._compute_dtype == self._param_dtype:
            return params
        def cast(a):
            if jnp.issubdtype(a.dtype, jnp.floating):
                return a.astype(self._compute_dtype)
            return a
        return jax.tree_util.tree_map(cast, params)

    @staticmethod
    def _vertex_out_mask(vertex, in_masks, xs, out_type):
        """Mask propagation through a non-layer graph vertex (the analog of
        DL4J GraphVertex.feedForwardMaskArrays): time-collapsing vertices
        drop the mask, DuplicateToTimeSeries adopts its reference input's
        mask, Reverse flips it, Stack/Unstack concat/slice along batch,
        everything else forwards the first non-None input mask."""
        if out_type.kind != Kind.RNN:
            return None
        vname = type(vertex).__name__
        if vname == "DuplicateToTimeSeriesVertex":
            return in_masks[1]
        if vname == "ReverseTimeSeriesVertex":
            m = in_masks[0]
            return None if m is None else jnp.flip(m, axis=1)
        if vname == "StackVertex":
            # output batch is the concat of input batches; so is its mask
            # (DL4J StackVertex.feedForwardMaskArrays). All-None stays
            # None; a mixed case substitutes all-ones for unmasked inputs.
            if all(m is None for m in in_masks):
                return None
            return jnp.concatenate(
                [jnp.ones(x.shape[:2], jnp.float32) if m is None else m
                 for m, x in zip(in_masks, xs)], axis=0)
        if vname == "UnstackVertex":
            m = in_masks[0]
            if m is None:
                return None
            n = m.shape[0] // vertex.stack_size
            return m[vertex.from_idx * n:(vertex.from_idx + 1) * n]
        return next((m for m in in_masks if m is not None), None)

    def _forward(self, params, state, inputs: Sequence, train, rng,
                 fmasks: Optional[Sequence] = None, stash_pre: bool = False,
                 carries: Optional[dict] = None):
        """Execute the DAG. Returns (activations dict, new_state,
        new_carries, per-vertex mask dict).

        Masks are routed per input path (ComputationGraph.setLayerMaskArrays
        semantics): each vertex sees the mask propagated from ITS inputs,
        not a globally shared one — a multi-input graph with differently
        masked sequence inputs applies each mask where it belongs.

        With `carries` (a dict, possibly empty), recurrent layer vertices
        run stateful via apply_seq and their final carry is returned — the
        graph analogs of rnnTimeStep / tBPTT stored state
        (ComputationGraph.java:2720, :2894).

        With stash_pre=True, the pre-head activation of each output vertex is
        stored under '__pre__<name>' so score() sees features, not
        post-activation output (the analog of DL4J output layers keeping
        preOutput for computeScore)."""
        from deeplearning4j_tpu.nn.multilayer import (
            _is_stateful_recurrent, _layer_call,
        )
        if self._vertex_types is None:
            self._vertex_types = self._resolve_types()
        params = self._cast_params(params)
        acts: Dict[str, Any] = {}
        masks: Dict[str, Any] = {}
        for i, name in enumerate(self.conf.network_inputs):
            acts[name] = _as_jnp(inputs[i], self._compute_dtype)
            masks[name] = (None if fmasks is None or i >= len(fmasks)
                           else fmasks[i])
        new_state = {}
        new_carries = {}
        out_set = set(self.conf.network_outputs) if stash_pre else ()
        for name in self._topo:
            vd = self.conf.vertices[name]
            xs = [acts[i] for i in vd.inputs]
            in_masks = [masks[i] for i in vd.inputs]
            if isinstance(vd.vertex, GraphVertexConf):
                acts[name] = vd.vertex.apply(*xs)
                masks[name] = self._vertex_out_mask(
                    vd.vertex, in_masks, xs, self._vertex_types[name])
                continue
            x = xs[0]
            need = self._pre_kind[name]
            src_t = self._input_type_of(vd.inputs[0])
            if need is not None and src_t.kind != need:
                x = preprocess_forward(src_t, need, x)
            sub_rng = None
            if rng is not None:
                rng, sub_rng = jax.random.split(rng)
            m = in_masks[0] if need == Kind.RNN else None
            if name in out_set:
                acts["__pre__" + name] = x
            layer_params = params.get(name, {})
            if train and sub_rng is not None and \
                    getattr(vd.vertex, "weight_noise", None) is not None:
                from deeplearning4j_tpu.nn.regularization import (
                    apply_weight_noise,
                )
                sub_rng, noise_rng = jax.random.split(sub_rng)
                layer_params = apply_weight_noise(vd.vertex, layer_params,
                                                  train, noise_rng)
            # per-vertex jax.checkpoint under gradient_checkpointing:
            # backward recomputes this vertex's activations (HBM for
            # FLOPs); inference forwards are untouched (train only)
            remat = train and self.conf.gradient_checkpointing
            if carries is not None and _is_stateful_recurrent(vd.vertex):
                y, carry = _layer_call(
                    vd.vertex, seq=True, train=train, remat=remat,
                    params=layer_params, x=x, carry=carries.get(name),
                    rng=sub_rng, mask=m)
                new_carries[name] = carry
                new_state[name] = state.get(name, {})
            else:
                y, s = _layer_call(
                    vd.vertex, seq=False, train=train, remat=remat,
                    params=layer_params, x=x, state=state.get(name, {}),
                    rng=sub_rng, mask=m)
                new_state[name] = s
            acts[name] = y
            masks[name] = (in_masks[0]
                           if self._vertex_types[name].kind == Kind.RNN
                           else None)
        return acts, new_state, new_carries, masks

    def _input_type_of(self, name: str) -> InputType:
        return self._vertex_types[name]

    # --------------------------------------------------------------- output
    def output(self, *inputs, train: bool = False):
        """Multi-output inference (ComputationGraph.output, :1759-1810)."""
        if self.params is None:
            raise RuntimeError(
                "Network is not initialized — call init() first")
        if self._output_fn is None:
            @jax.jit
            def _out(params, state, inputs):
                acts, _, _, _ = self._forward(params, state, inputs, False,
                                              None)
                return tuple(acts[o] for o in self.conf.network_outputs)
            self._output_fn = _out
        outs = self._output_fn(self.params, self.state,
                               tuple(_as_jnp(x, self._compute_dtype) for x in inputs))
        return outs[0] if len(outs) == 1 else outs

    def feed_forward(self, *inputs, train: bool = False):
        acts, _, _, _ = self._forward(self.params, self.state, inputs, train,
                                      None)
        return acts

    # ------------------------------------------------------------------ fit
    def _score_fn(self, params, state, inputs, labels, fmasks, lmasks, train,
                  rng, carries=None):
        params_c = self._cast_params(params)
        acts, new_state, new_carries, masks = self._forward(
            params_c, state, inputs, train, rng, fmasks, stash_pre=True,
            carries=carries)
        total = jnp.asarray(0.0, jnp.float32)
        for i, out_name in enumerate(self.conf.network_outputs):
            vd = self.conf.vertices[out_name]
            feat = acts["__pre__" + out_name]
            lmask = None
            if lmasks is not None and lmasks[i] is not None:
                lmask = lmasks[i]
            elif self._vertex_types[out_name].kind == Kind.RNN:
                # RNN output with no label mask: fall back to the feature
                # mask propagated along THIS output's input path
                lmask = masks[vd.inputs[0]]
            lab = _as_jnp(labels[i], self._compute_dtype)
            s = vd.vertex.score(params_c.get(out_name, {}), feat, lab,
                                train=train, rng=None, mask=lmask)
            # keep f64 under float64 gradient checking; f32 otherwise
            total = total + s.astype(jnp.promote_types(jnp.float32, s.dtype))
        for name, p in params.items():
            vd = self.conf.vertices[name]
            if isinstance(vd.vertex, LayerConf):
                total = total + vd.vertex.regularization_score(p)
        return total, (new_state, new_carries)

    def _make_train_step(self):
        from deeplearning4j_tpu.nn.regularization import (
            apply_constraints, constraint_map, has_constraints,
        )
        tx = self._tx
        layer_map = constraint_map(self)
        constrained = has_constraints(layer_map.values())
        plan = self._plan   # GSPMD plan: sharding constraints in-jit

        def step(params, opt_state, state, inputs, labels, fmasks, lmasks,
                 rng, carries):
            def loss_fn(p):
                return self._score_fn(p, state, inputs, labels, fmasks,
                                      lmasks, True, rng, carries=carries)
            (loss, (new_state, new_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if plan is not None:
                # pin grads to the ZeRO/TP compute layout: the single
                # hint from which XLA derives reduce-scatter -> sharded
                # update -> all-gather (parallel/plan.py)
                grads = plan.constrain_grads(grads)
            updates, new_opt = tx.update(grads, opt_state, params)
            if plan is not None:
                updates = plan.constrain_grads(updates)
            new_params = optax.apply_updates(params, updates)
            if constrained:     # post-update projection (DL4J applyConstraints)
                new_params = apply_constraints(layer_map, new_params)
            if plan is not None:
                new_params = plan.constrain_params(new_params)
                new_opt = plan.constrain_opt(new_opt, new_params)
                new_state = plan.constrain_replicated(new_state)
            return new_params, new_opt, new_state, loss, new_carries

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def fit(self, data, epochs: int = 1, scan_steps: Optional[int] = None,
            accumulate_steps: int = 1, plan=None):
        """Train on a MultiDataSet / DataSet / iterator of either
        (ComputationGraph.fit, :1015).

        scan_steps > 1 fuses that many optimizer steps into one jit via
        lax.scan with a one-chunk-deferred loss fetch (input-pipelined fit;
        see MultiLayerNetwork.fit) — bit-identical math/RNG to the per-call
        path. Default: 10 on TPU, 1 on CPU (measured, PERF.md);
        $DL4J_TPU_SCAN_STEPS overrides.

        accumulate_steps > 1: gradient accumulation — K micro-batch
        gradients averaged into ONE optimizer step inside one jit (see
        MultiLayerNetwork.fit; mutually exclusive with scan_steps > 1,
        not applicable to tbptt)."""
        if self.params is None:
            self.init()
        # donated-buffer safety: see util/params.owned_leaf (params from a
        # checkpoint or import may alias numpy memory the donating step
        # would otherwise free); under a GSPMD plan the laundered copies
        # additionally land on the plan placements (docs/PARALLELISM.md)
        from deeplearning4j_tpu.parallel.plan import active_plan
        if plan is None:
            plan = active_plan()
        if plan is None and self._plan is None:
            # deliberately inlined (mirrors _engage_plan_impl's no-plan
            # branch): the donated-aliasing lint contract requires the
            # own_tree laundering to live IN the module that builds the
            # donating steps, not only behind the shared impl — keep in
            # sync with nn/multilayer._engage_plan_impl
            self.params = param_util.own_tree(self.params)
            self.state = param_util.own_tree(self.state)
            self.opt_state = param_util.own_tree(self.opt_state)
        else:
            self._engage_plan(plan)
        if self._train_step is None:
            self._train_step = self._make_train_step()
        if accumulate_steps > 1:
            if self.conf.backprop_type == "tbptt":
                raise ValueError("accumulate_steps does not apply to "
                                 "tbptt (chunked-time) training")
            if scan_steps is not None and scan_steps > 1:
                raise ValueError("accumulate_steps and scan_steps are "
                                 "mutually exclusive (one fuses K "
                                 "optimizer steps, the other folds K "
                                 "micro-batches into one step)")
            scan_steps = 1
        if scan_steps is None:
            scan_steps = _default_scan_steps()
        rng = jax.random.PRNGKey(self.conf.seed + 331 * (self.epoch_count + 1))
        tbptt = self.conf.backprop_type == "tbptt"
        # device-side normalization (data/normalization.py
        # engaged_device_affine; see MultiLayerNetwork.fit): the affine
        # pre-processor is applied on device, raw (uint8) features ship
        # over the link
        from deeplearning4j_tpu.data.normalization import (
            engaged_device_affine)
        with engaged_device_affine(data, self.listeners) as aff:
            if aff is not None:
                self._input_affine = (jnp.asarray(aff[0]),
                                      jnp.asarray(aff[1]))
            copy_marked = []
            if not tbptt and (accumulate_steps > 1 or (
                    scan_steps > 1
                    and not _scan_incompatible_listeners(self.listeners))):
                # the stacking fits hold K live batches before one
                # transfer — shared-memory ring sources must yield copies
                # (data/pipeline.mark_copy_for_stacking)
                from deeplearning4j_tpu.data.pipeline import (
                    mark_copy_for_stacking)
                copy_marked = mark_copy_for_stacking(data)
            from deeplearning4j_tpu.monitor import goodput
            gp_session = goodput.fit_begin("graph/fit")
            try:
                from deeplearning4j_tpu import monitor
                for _ in range(epochs):
                    for lst in self.listeners:
                        lst.on_epoch_start(self, self.epoch_count)
                    with monitor.span("train/epoch",
                                      epoch=self.epoch_count):
                        if not tbptt and accumulate_steps > 1:
                            rng = self._fit_epoch_accum(data, rng,
                                                        accumulate_steps)
                        elif not tbptt and scan_steps > 1:
                            rng = self._fit_epoch_scan(data, rng, scan_steps)
                        else:
                            rng = self._fit_epoch_per_call(data, rng, tbptt)
                    for lst in self.listeners:
                        lst.on_epoch_end(self, self.epoch_count)
                    self.epoch_count += 1
                    if hasattr(data, "reset"):
                        data.reset()
            finally:
                goodput.fit_end(gp_session)
                self._input_affine = None
                for it_ in copy_marked:
                    it_._copy = False
        return self

    def _mds_stream(self, data):
        """MultiDataSet stream for one epoch: a prefetch worker thread
        overlaps host ETL + the bf16 host cast + the H2D transfer with
        device compute (the reference wraps every fit in an async iterator
        by default — MultiLayerNetwork.java:1272-1274, same contract for
        graphs at ComputationGraph.java:1015), DL4J_TPU_PREFETCH_DEPTH
        batches deep (default 2: double-buffered H2D).
        DL4J_TPU_FIT_PREFETCH=0 or DL4J_TPU_PREFETCH_DEPTH=0 disables
        the thread (the latter keeps synchronous staging)."""
        from deeplearning4j_tpu.data.async_iterator import (
            fit_prefetch_enabled, host_cast, prefetch_iterable,
        )
        if not fit_prefetch_enabled() \
                or getattr(data, "async_supported", True) is False:
            return self._iter_data(data)
        cast = self._compute_dtype \
            if np.dtype(self._compute_dtype).itemsize == 2 else None
        # device-norm engaged: features reach the device UNCAST so the
        # affine normalizes the full-precision values (normalize-then-
        # cast); labels still ship 16-bit
        fcast = None if self._input_affine is not None else cast
        # under a GSPMD plan the worker thread stages straight onto the
        # mesh (batch dim over "data"); ragged tails degrade to the
        # default device via the shared fallback (parallel/plan.put_batch)
        # instead of killing the prefetch thread
        if self._plan is not None:
            from deeplearning4j_tpu.parallel.plan import put_batch
            dev = self._plan.batch_sharding()
            put_fn = put_batch
        else:
            dev = jax.local_devices()[0]
            put_fn = jax.device_put

        def stage(mds):
            def put(a):
                return None if a is None else put_fn(a, dev)
            return MultiDataSet(
                tuple(put(host_cast(f, fcast)) for f in mds.features),
                tuple(put(host_cast(l, cast)) for l in mds.labels),
                None if mds.features_masks is None
                else tuple(put(m) for m in mds.features_masks),
                None if mds.labels_masks is None
                else tuple(put(m) for m in mds.labels_masks))

        return prefetch_iterable(self._iter_data(data), stage)

    def _fit_epoch_per_call(self, data, rng, tbptt):
        from deeplearning4j_tpu import monitor
        from deeplearning4j_tpu.monitor import goodput
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        etl_start = time.perf_counter()
        for mds in self._mds_stream(data):
            step_start = time.perf_counter()
            etl_ms = (step_start - etl_start) * 1e3
            monitor.add_span("train/etl", etl_start, step_start,
                             iteration=self.iteration_count)
            inputs = self._shard_tuple(
                tuple(self._stage_x(f) for f in mds.features))
            labels = self._shard_tuple(
                tuple(_as_jnp(l, self._compute_dtype) for l in mds.labels))
            fmasks = self._shard_tuple(
                None if mds.features_masks is None else tuple(
                    _as_jnp(m) for m in mds.features_masks))
            lmasks = self._shard_tuple(
                None if mds.labels_masks is None else tuple(
                    _as_jnp(m) for m in mds.labels_masks))
            bs = int(np.shape(mds.features[0])[0])
            if tbptt:
                rng = self._fit_tbptt_batch(inputs, labels, fmasks,
                                            lmasks, rng, etl_ms, bs)
            else:
                rng, sub = jax.random.split(rng)
                (self.params, self.opt_state, self.state, loss,
                 _) = self._train_step(
                    self.params, self.opt_state, self.state, inputs,
                    labels, fmasks, lmasks, sub, None)
                sync_start = time.perf_counter()
                # block for device completion FIRST (goodput:
                # step_compute; banks per-shard barrier wait under a
                # plan), so the host_sync span below covers only the
                # narrow D2H fetch
                goodput.device_wait(loss)
                fetch_start = time.perf_counter()
                monitor.add_span("train/device_wait", sync_start,
                                 fetch_start)
                # graftlint: disable=host-sync-in-hot-path -- the step's ONE budgeted loss fetch (the deliberate per-iteration sync; PERF.md) — bracketed by the train/host_sync span
                self._score = float(loss)
                step_end = time.perf_counter()
                monitor.add_span("train/host_sync", fetch_start, step_end)
                monitor.add_span("train/step", step_start, step_end,
                                 iteration=self.iteration_count,
                                 score=self._score, batch_size=bs)
                if xla_ledger.enabled():
                    key = (id(self._train_step), xla_ledger.shape_key(
                        (inputs, labels, fmasks, lmasks)))
                    fresh = key not in self._ledger_cache
                    rec = xla_ledger.capture_cached(
                        self._ledger_cache, key,
                        "graph/train_step", self._train_step,
                        (self.params, self.opt_state, self.state, inputs,
                         labels, fmasks, lmasks, sub, None),
                        examples_per_call=bs)
                    if not fresh:
                        # debut wall time includes the jit compile —
                        # only steady-state steps feed the MFU gauge
                        xla_ledger.observe_step(rec,
                                                step_end - step_start)
                _record_iteration(self._score, bs,
                                  step_seconds=step_end - step_start,
                                  sync_seconds=step_end - fetch_start)
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count,
                                       self.epoch_count, self._score,
                                       etl_ms, bs)
                self.iteration_count += 1
            etl_start = time.perf_counter()
        return rng

    def _make_scan_step(self):
        from deeplearning4j_tpu.nn.regularization import (
            apply_constraints, constraint_map, has_constraints,
        )
        tx = self._tx
        layer_map = constraint_map(self)
        constrained = has_constraints(layer_map.values())

        plan = self._plan   # GSPMD plan: sharding constraints in-jit

        def kstep(params, opt_state, state, inputs, labels, fmasks, lmasks,
                  subs):
            def body(carry, batch):
                params, opt_state, state = carry
                cin, clab, cfm, clm, sub = batch
                def loss_fn(p):
                    return self._score_fn(p, state, cin, clab, cfm, clm,
                                          True, sub, carries=None)
                (loss, (new_state, _)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                if plan is not None:
                    grads = plan.constrain_grads(grads)
                updates, new_opt = tx.update(grads, opt_state, params)
                if plan is not None:
                    updates = plan.constrain_grads(updates)
                new_params = optax.apply_updates(params, updates)
                if constrained:
                    new_params = apply_constraints(layer_map, new_params)
                if plan is not None:
                    new_params = plan.constrain_params(new_params)
                    new_opt = plan.constrain_opt(new_opt, new_params)
                    new_state = plan.constrain_replicated(new_state)
                return (new_params, new_opt, new_state), loss

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state),
                (inputs, labels, fmasks, lmasks, subs))
            return params, opt_state, state, losses

        return jax.jit(kstep, donate_argnums=(0, 1, 2))

    def _mds_to_dev(self, mds):
        """MultiDataSet -> device operand tuples; the ONE staging rule
        the per-call, scan and accumulation fit paths share."""
        return (tuple(self._stage_x(f) for f in mds.features),
                tuple(_as_jnp(l, self._compute_dtype) for l in mds.labels),
                None if mds.features_masks is None else tuple(
                    _as_jnp(m) for m in mds.features_masks),
                None if mds.labels_masks is None else tuple(
                    _as_jnp(m) for m in mds.labels_masks))

    @staticmethod
    def _mds_sig(mds):
        shapes = lambda t: None if t is None else tuple(
            np.shape(a) for a in t)
        return (shapes(mds.features), shapes(mds.labels),
                shapes(mds.features_masks), shapes(mds.labels_masks))

    def _make_accum_step(self):
        """K micro-batch gradients averaged into ONE optimizer step (see
        MultiLayerNetwork._make_accum_step)."""
        from deeplearning4j_tpu.nn.regularization import (
            apply_constraints, constraint_map, has_constraints,
        )
        tx = self._tx
        layer_map = constraint_map(self)
        constrained = has_constraints(layer_map.values())

        plan = self._plan   # GSPMD plan: sharding constraints in-jit

        def kaccum(params, opt_state, state, inputs, labels, fmasks,
                   lmasks, subs):
            k = subs.shape[0]

            def body(carry, batch):
                gsum, state = carry
                cin, clab, cfm, clm, sub = batch
                def loss_fn(p):
                    return self._score_fn(p, state, cin, clab, cfm, clm,
                                          True, sub, carries=None)
                (loss, (new_state, _)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                gsum = jax.tree_util.tree_map(jnp.add, gsum, grads)
                if plan is not None:
                    # the accumulator carries in the ZeRO layout: micro-
                    # batch grads reduce-scatter into it instead of ever
                    # materializing whole per chip
                    gsum = plan.constrain_grads(gsum)
                return (gsum, new_state), loss

            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
            (gsum, state), losses = jax.lax.scan(
                body, (zeros, state), (inputs, labels, fmasks, lmasks,
                                       subs))
            grads = jax.tree_util.tree_map(lambda g: g / k, gsum)
            updates, new_opt = tx.update(grads, opt_state, params)
            if plan is not None:
                updates = plan.constrain_grads(updates)
            new_params = optax.apply_updates(params, updates)
            if constrained:
                new_params = apply_constraints(layer_map, new_params)
            if plan is not None:
                new_params = plan.constrain_params(new_params)
                new_opt = plan.constrain_opt(new_opt, new_params)
                state = plan.constrain_replicated(state)
            return new_params, new_opt, state, jnp.mean(losses)

        return jax.jit(kaccum, donate_argnums=(0, 1, 2))

    def _fit_epoch_accum(self, data, rng, K):
        """One optimizer step per K stacked micro-batches; chunking and
        ragged-tail handling as in _fit_epoch_scan, lockstep listener
        callbacks when a model-reading listener is attached."""
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        last_sync = [None]

        def process(p):
            loss, bs, etl_ms, rec = p
            self._score = float(loss)
            if xla_ledger.enabled():
                now = time.perf_counter()
                if rec is not None and last_sync[0] is not None:
                    xla_ledger.observe_step(rec, now - last_sync[0])
                last_sync[0] = now
            _record_iteration(self._score, bs)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count,
                                   self.epoch_count, self._score, etl_ms,
                                   bs)
            self.iteration_count += 1

        def dispatch(group, etl_ms):
            nonlocal rng
            subs = []
            for _ in group:
                rng, sub = jax.random.split(rng)
                subs.append(sub)
            items = [self._mds_to_dev(m) for m in group]
            inputs, labels, fmasks, lmasks = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *items)
            inputs = self._shard_tuple(inputs, stacked=True)
            labels = self._shard_tuple(labels, stacked=True)
            fmasks = self._shard_tuple(fmasks, stacked=True)
            lmasks = self._shard_tuple(lmasks, stacked=True)
            sig = ("accum", fmasks is not None, lmasks is not None)
            if sig not in self._scan_step:
                self._scan_step[sig] = self._make_accum_step()
            kstep = self._scan_step[sig]
            subs_d = jnp.stack(subs)
            (self.params, self.opt_state, self.state,
             loss) = kstep(
                self.params, self.opt_state, self.state, inputs, labels,
                fmasks, lmasks, subs_d)
            bs = int(np.shape(group[0].features[0])[0]) * len(group)
            rec = None
            if xla_ledger.enabled():
                key = (id(kstep), xla_ledger.shape_key(
                    (inputs, labels, fmasks, lmasks)))
                fresh = key not in self._ledger_cache
                rec = xla_ledger.capture_cached(
                    self._ledger_cache, key,
                    "graph/accum_step", kstep,
                    (self.params, self.opt_state, self.state, inputs,
                     labels, fmasks, lmasks, subs_d),
                    examples_per_call=bs,
                    steps_per_call=len(group))
                if fresh:
                    last_sync[0] = None   # exclude the AOT compile interval
            return (loss, bs, etl_ms, rec)

        # _iter_data, not _mds_stream: dispatch stacks K host batches
        # into ONE transfer; the prefetch stream's per-batch device_put
        # would round-trip each micro-batch through the host (same rule
        # as _fit_epoch_scan)
        _run_scan_pipeline(self._iter_data(data), self._mds_sig, dispatch,
                           process, K,
                           defer=not _scan_incompatible_listeners(
                               self.listeners))
        return rng

    def _fit_epoch_scan(self, data, rng, K):
        """Input-pipelined epoch over MultiDataSets: consecutive same-shape
        batches are stacked and run as one scan-of-K jit; the loss fetch is
        deferred one chunk so host stacking overlaps device compute. Ragged
        tails fall back to the per-call step."""
        if _scan_incompatible_listeners(self.listeners):
            return self._fit_epoch_per_call(data, rng, False)
        from deeplearning4j_tpu.monitor import xla as xla_ledger
        last_sync = [None]

        def process(p):
            losses, bs, etl_ms, rec = p
            arr = np.asarray(losses)
            if xla_ledger.enabled():
                # steady-state chunk wall = spacing between chunk syncs;
                # the stamp advances on EVERY chunk so a ragged tail
                # can't leak into the next interval (see
                # MultiLayerNetwork._fit_epoch_scan)
                now = time.perf_counter()
                if rec is not None and last_sync[0] is not None:
                    xla_ledger.observe_step(rec, now - last_sync[0])
                last_sync[0] = now
            for loss in arr:
                # graftlint: disable=host-sync-in-hot-path -- chunk losses are already host-resident (np.asarray above IS the deferred chunk sync); this is per-iteration bookkeeping
                self._score = float(loss)
                _record_iteration(self._score, bs)
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration_count,
                                       self.epoch_count, self._score,
                                       etl_ms, bs)
                self.iteration_count += 1
                etl_ms = 0.0

        def to_dev(mds):
            return (tuple(self._stage_x(f) for f in mds.features),
                    tuple(_as_jnp(l, self._compute_dtype) for l in mds.labels),
                    None if mds.features_masks is None else tuple(
                        _as_jnp(m) for m in mds.features_masks),
                    None if mds.labels_masks is None else tuple(
                        _as_jnp(m) for m in mds.labels_masks))

        def dispatch(group, etl_ms):
            nonlocal rng
            subs = []
            for _ in group:
                rng, sub = jax.random.split(rng)
                subs.append(sub)
            bs = int(np.shape(group[0].features[0])[0])
            if len(group) < K:
                # ragged tail / shape-change remainder: reuse the compiled
                # per-call step instead of a one-off scan-of-len(group)
                losses = []
                for mds, sub in zip(group, subs):
                    inputs, labels, fmasks, lmasks = to_dev(mds)
                    inputs = self._shard_tuple(inputs)
                    labels = self._shard_tuple(labels)
                    fmasks = self._shard_tuple(fmasks)
                    lmasks = self._shard_tuple(lmasks)
                    (self.params, self.opt_state, self.state, loss,
                     _) = self._train_step(
                        self.params, self.opt_state, self.state, inputs,
                        labels, fmasks, lmasks, sub, None)
                    losses.append(loss)
                return (jnp.stack(losses), bs, etl_ms, None)
            items = [to_dev(m) for m in group]
            inputs, labels, fmasks, lmasks = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *items)
            inputs = self._shard_tuple(inputs, stacked=True)
            labels = self._shard_tuple(labels, stacked=True)
            fmasks = self._shard_tuple(fmasks, stacked=True)
            lmasks = self._shard_tuple(lmasks, stacked=True)
            sig = (len(group), fmasks is not None, lmasks is not None)
            if sig not in self._scan_step:
                self._scan_step[sig] = self._make_scan_step()
            kstep = self._scan_step[sig]
            subs_d = jnp.stack(subs)
            (self.params, self.opt_state, self.state,
             losses) = kstep(
                self.params, self.opt_state, self.state, inputs, labels,
                fmasks, lmasks, subs_d)
            rec = None
            if xla_ledger.enabled():
                key = (id(kstep), xla_ledger.shape_key(
                    (inputs, labels, fmasks, lmasks)))
                fresh = key not in self._ledger_cache
                rec = xla_ledger.capture_cached(
                    self._ledger_cache, key,
                    "graph/scan_step", kstep,
                    (self.params, self.opt_state, self.state, inputs,
                     labels, fmasks, lmasks, subs_d),
                    examples_per_call=bs * len(group),
                    steps_per_call=len(group))
                if fresh:
                    last_sync[0] = None   # exclude the AOT compile interval
            return (losses, bs, etl_ms, rec)

        def sig_of(mds):
            shapes = lambda t: None if t is None else tuple(
                np.shape(a) for a in t)
            return (shapes(mds.features), shapes(mds.labels),
                    shapes(mds.features_masks), shapes(mds.labels_masks))

        _run_scan_pipeline(self._iter_data(data), sig_of, dispatch, process,
                           K)
        return rng

    def _fit_tbptt_batch(self, inputs, labels, fmasks, lmasks, rng, etl_ms,
                         bs):
        """Truncated BPTT over one batch: chunk the time axis of every
        sequence input/label/mask, carry RNN state across chunks with
        stop_gradient at the boundaries (ComputationGraph.java:2894
        doTruncatedBPTT)."""
        fwd = self.conf.tbptt_fwd_length
        in_types = [self._vertex_types[n] for n in self.conf.network_inputs]
        seq_lengths = [f.shape[1] for t, f in zip(in_types, inputs)
                       if t.kind == Kind.RNN]
        if not seq_lengths:
            raise ValueError("tbptt backprop requires at least one RNN "
                             "(B, T, F) network input")
        if len(set(seq_lengths)) > 1:
            raise ValueError(
                f"tbptt requires all RNN inputs to share one sequence "
                f"length, got {seq_lengths} — chunking cannot be aligned "
                f"across inputs of different T")
        T = seq_lengths[0]

        def slice_t(arr, t0, t1, is_mask=False):
            # sequences are rank-3 (B,T,F); masks are rank-2 (B,T). A rank-2
            # LABEL is per-example (B,C) and must not be time-sliced even if
            # C happens to equal T (DL4J slices by rank the same way).
            if arr is None:
                return arr
            if np.ndim(arr) >= 3 and arr.shape[1] == T:
                return arr[:, t0:t1]
            if is_mask and np.ndim(arr) == 2 and arr.shape[1] == T:
                return arr[:, t0:t1]
            return arr

        carries = {}
        for t0 in range(0, T, fwd):
            t1 = min(t0 + fwd, T)
            cin = tuple(slice_t(f, t0, t1) for f in inputs)
            clab = tuple(slice_t(l, t0, t1) for l in labels)
            cfm = None if fmasks is None else tuple(
                slice_t(m, t0, t1, is_mask=True) for m in fmasks)
            clm = None if lmasks is None else tuple(
                slice_t(m, t0, t1, is_mask=True) for m in lmasks)
            rng, sub = jax.random.split(rng)
            (self.params, self.opt_state, self.state, loss,
             new_carries) = self._train_step(
                self.params, self.opt_state, self.state, cin, clab, cfm,
                clm, sub, carries)
            carries = jax.tree_util.tree_map(jax.lax.stop_gradient,
                                             new_carries)
            # graftlint: disable=host-sync-in-hot-path -- the tbptt chunk's one budgeted loss fetch
            self._score = float(loss)
            _record_iteration(self._score, bs)
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration_count,
                                   self.epoch_count, self._score, etl_ms, bs)
            self.iteration_count += 1
            etl_ms = 0.0
        return rng

    def _iter_data(self, data):
        if isinstance(data, (tuple, list)) and len(data) == 2 \
                and all(hasattr(d, "shape") for d in data):
            # (features, labels) ARRAY pair convenience, as
            # MultiLayerNetwork.fit; anything else 2-long (a batch list,
            # tuples of per-input arrays) iterates normally. Arrays pass
            # through as-is — no host round-trip for device-resident data.
            data = MultiDataSet((data[0],), (data[1],), None, None)
        if isinstance(data, MultiDataSet):
            yield data
        elif isinstance(data, DataSet):
            yield MultiDataSet((data.features,), (data.labels,),
                               None if data.features_mask is None else (data.features_mask,),
                               None if data.labels_mask is None else (data.labels_mask,))
        else:
            for item in data:
                yield from self._iter_data(item)

    # -------------------------------------------------------------- scoring
    def score(self, mds: Optional[MultiDataSet] = None) -> float:
        if mds is None:
            return self._score if self._score is not None else float("nan")
        if isinstance(mds, DataSet):
            mds = MultiDataSet((mds.features,), (mds.labels,))
        loss, _ = self._score_fn(
            self.params, self.state,
            tuple(_as_jnp(f, self._compute_dtype) for f in mds.features),
            tuple(_as_jnp(l, self._compute_dtype) for l in mds.labels),
            None, None, False, None)
        return float(loss)

    def evaluate_roc(self, data, batch_size: int = 32):
        """Binary ROC on the (single-output) graph (DL4J evaluateROC)."""
        from deeplearning4j_tpu.eval.roc import ROC
        return self._evaluate_with(ROC(), data, batch_size)

    def evaluate_roc_multi_class(self, data, batch_size: int = 32):
        """One-vs-all per-class ROC (DL4J evaluateROCMultiClass)."""
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        return self._evaluate_with(ROCMultiClass(), data, batch_size)

    def _evaluate_with(self, ev, data, batch_size: int = 32):
        """Feed an eval accumulator from the first output, chunked by
        batch_size and excluding mask-padded entries."""
        from deeplearning4j_tpu.nn.multilayer import _masked_eval_pair
        for mds in self._iter_data(data):
            labels = np.asarray(mds.labels[0])
            lm = None if mds.labels_masks is None else mds.labels_masks[0]
            n = labels.shape[0]
            for i in range(0, n, batch_size):
                out = self.output(*(f[i:i + batch_size]
                                    for f in mds.features))
                out = out[0] if isinstance(out, (tuple, list)) else out
                ev.eval(*_masked_eval_pair(
                    labels[i:i + batch_size], np.asarray(out),
                    None if lm is None else lm[i:i + batch_size]))
        if hasattr(data, "reset"):
            data.reset()
        return ev

    def evaluate(self, data, batch_size: int = 32):
        """First-output classification evaluation (DL4J evaluate);
        mask-padded steps excluded, chunked by batch_size."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        return self._evaluate_with(Evaluation(), data, batch_size)

    # ----------------------------------------------------- recurrent state
    def rnn_time_step(self, *inputs):
        """Stateful streaming inference over the DAG (ComputationGraph
        rnnTimeStep, ComputationGraph.java:2720). Each input is (B, F) for
        one step or (B, T, F) for several; recurrent vertex state persists
        across calls until rnn_clear_previous_state()."""
        if not hasattr(self, "_rnn_carries"):
            self._rnn_carries = {}
        if self._vertex_types is None:
            self._vertex_types = self._resolve_types()
        in_types = [self._vertex_types[n] for n in self.conf.network_inputs]
        singles = []
        prep = []
        for t, x in zip(in_types, inputs):
            x = _as_jnp(x, self._compute_dtype)
            single = t.kind == Kind.RNN and x.ndim == 2
            singles.append(single)
            prep.append(x[:, None, :] if single else x)
        if getattr(self, "_rnn_step_fn", None) is None:
            # jitted once; jax re-traces automatically when the carry
            # pytree structure changes (first call: empty dict)
            @jax.jit
            def _stepfn(params, state, prep, carries):
                acts, _, new_carries, _ = self._forward(
                    params, state, prep, False, None, carries=carries)
                return ({o: acts[o] for o in self.conf.network_outputs},
                        new_carries)
            self._rnn_step_fn = _stepfn
        out_acts, new_carries = self._rnn_step_fn(
            self.params, self.state, tuple(prep), self._rnn_carries)
        acts = out_acts
        self._rnn_carries = new_carries
        outs = []
        for o in self.conf.network_outputs:
            y = acts[o]
            if any(singles) and y.ndim == 3:
                y = y[:, -1, :]
            outs.append(y)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def rnn_clear_previous_state(self):
        self._rnn_carries = {}

    # -------------------------------------------------------------- summary
    def summary(self) -> str:
        """Vertex table in topological order: name, type, inputs, output
        shape, parameter count (ComputationGraph summary analog)."""
        if self.params is None:
            raise RuntimeError("init() the network before summary()")
        types = self._vertex_types or self._resolve_types()
        self._vertex_types = types
        rows = [("vertex", "type", "inputs", "out", "params")]
        total = 0
        for name in self._topo:
            vd = self.conf.vertices[name]
            n = param_util.num_params(self.params.get(name, {}))
            total += n
            rows.append((name, type(vd.vertex).__name__,
                         ",".join(vd.inputs),
                         "x".join(map(str, types[name].shape)), f"{n:,}"))
        return param_util.format_param_table(rows, total)

    # --------------------------------------------------------------- memory
    def memory_report(self, batch_size: int = 32, with_compiled: bool = True):
        """Per-vertex analytic memory estimate + exact XLA compiled-step HBM
        (DL4J NetworkMemoryReport analog — see util/memory.py)."""
        from deeplearning4j_tpu.util.memory import build_memory_report
        return build_memory_report(self, batch_size, with_compiled)

    def copy(self) -> "ComputationGraph":
        """Clone with copied parameter/state pytrees (MultiLayerNetwork.copy
        analog for graphs)."""
        clone = ComputationGraph(self.conf)
        if self.params is not None:
            clone._vertex_types = self._vertex_types or self._resolve_types()
            clone._pre_kind = self._pre_kind
            # materialize NEW buffers: the original's arrays are donated by
            # its train step and would be deleted out from under the clone
            clone.params = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True), self.params)
            clone.state = jax.tree_util.tree_map(
                lambda a: jnp.array(a, copy=True), self.state)
            clone._build_optimizer()
        return clone

    # --------------------------------------------------------------- params
    def num_params(self) -> int:
        return param_util.num_params(self.params)

    def params_flat(self):
        return param_util.params_to_flat(self.params)

    def set_params_flat(self, flat):
        self.params = param_util.flat_to_params(flat, self.params)
