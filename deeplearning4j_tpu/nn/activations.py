"""Activation functions.

Capability parity with DL4J's IActivation implementations (consumed from
nd4j-api; enumerated in deeplearning4j-nn layer configs via `Activation`).
Here each activation is a pure jnp function resolved by name through a
registry — XLA fuses these into adjacent matmuls, so there is no per-activation
kernel object like DL4J's IActivation classes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-12


def identity(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def leakyrelu(x, alpha: float = 0.01):
    return jnp.where(x >= 0, x, alpha * x)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    return jax.nn.gelu(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh_(x):
    return jnp.tanh(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x):
    # DL4J ActivationRationalTanh: 1.7159 * tanh_approx(2x/3)
    ax = jnp.abs(2.0 * x / 3.0)
    approx = jnp.sign(x) * (1.0 - 1.0 / (1.0 + ax + ax * ax + 1.41645 * ax**4))
    return 1.7159 * approx


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))

def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def swish(x):
    return jax.nn.swish(x)


def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


def cube(x):
    return x * x * x


def thresholdedrelu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0)


ACTIVATIONS = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh_,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softmax": softmax,
    "softplus": softplus,
    "softsign": softsign,
    "swish": swish,
    "mish": mish,
    "cube": cube,
    "thresholdedrelu": thresholdedrelu,
}


def get_activation(name_or_fn):
    """Resolve an activation by name (case-insensitive) or pass through a callable."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in ACTIVATIONS:
        raise ValueError(f"Unknown activation '{name_or_fn}'. Known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]
