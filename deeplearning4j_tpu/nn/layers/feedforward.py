"""Feed-forward layer family.

Parity targets (config semantics, not code):
- DenseLayer        <- DL4J nn/conf/layers/DenseLayer.java + nn/layers/feedforward/dense/
- EmbeddingLayer    <- nn/conf/layers/EmbeddingLayer.java (one-hot index -> row lookup)
- ActivationLayer   <- nn/conf/layers/ActivationLayer.java
- DropoutLayer      <- nn/conf/layers/DropoutLayer.java
- OutputLayer       <- nn/conf/layers/OutputLayer.java (dense + loss head)
- LossLayer         <- nn/conf/layers/LossLayer.java (loss head, no params)
- AutoEncoder       <- nn/conf/layers/AutoEncoder.java (denoising AE pretrain layer)

All matmuls are (B, in) @ (in, out) — MXU-shaped; dtype follows the network's
compute dtype (bf16 on TPU by default, fp32 for parity runs).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import InputType, Kind, LayerConf, register_layer
from deeplearning4j_tpu.nn.initializers import get_initializer
from deeplearning4j_tpu.nn.losses import get_loss


@register_layer
@dataclasses.dataclass(frozen=True)
class DenseLayer(LayerConf):
    n_out: int = 0
    n_in: Optional[int] = None          # inferred from input when None
    activation: str = "identity"
    weight_init: str = "xavier"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (n_in, self.n_out), n_in, self.n_out, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class ElementWiseMultiplicationLayer(LayerConf):
    """out = activation(x * w + b) with a learnable per-feature weight
    vector w and bias b; input and output size are equal
    (DL4J nn/conf/layers/misc/ElementWiseMultiplicationLayer.java, impl
    nn/layers/feedforward/elementwise/ElementWiseMultiplicationLayer.java,
    params ElementWiseParamInitializer — W is a length-nOut vector)."""
    n_out: int = 0                      # == n_in; inferred when 0
    n_in: Optional[int] = None
    activation: str = "identity"
    weight_init: str = "xavier"
    bias_init: float = 0.0

    def output_type(self, input_type: InputType) -> InputType:
        n = self.n_out or input_type.features
        if self.n_in and self.n_in != n:
            raise ValueError("ElementWiseMultiplicationLayer requires "
                             f"n_in == n_out, got {self.n_in} vs {n}")
        return InputType.feed_forward(n)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n = self.n_out or input_type.features
        if input_type.features != n:
            raise ValueError("ElementWiseMultiplicationLayer requires "
                             f"n_in == n_out, got {input_type.features} "
                             f"vs {n}")
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (n,), n, n, dtype),
                  "b": jnp.full((n,), self.bias_init, dtype)}
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        return get_activation(self.activation)(
            x * params["W"] + params["b"]), state


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(LayerConf):
    """Index -> embedding row. Input: (B,) or (B,1) integer indices.
    DL4J's EmbeddingLayer is mathematically a one-hot matmul; on TPU we use a
    gather (jnp.take) which XLA lowers to a dynamic-slice — no dense one-hot."""
    n_out: int = 0
    n_in: Optional[int] = None          # vocab size; must be set or inferred
    activation: str = "identity"
    weight_init: str = "xavier"
    has_bias: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (n_in, self.n_out), n_in, self.n_out, dtype)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2 and idx.shape[-1] == 1:
            idx = idx[..., 0]
        y = jnp.take(params["W"], idx, axis=0)
        if self.has_bias:
            y = y + params["b"]
        return get_activation(self.activation)(y), state


@register_layer
@dataclasses.dataclass(frozen=True)
class ActivationLayer(LayerConf):
    """Standalone activation (DL4J ActivationLayer). `alpha` parameterizes
    leaky/elu-style activations (DL4J ActivationLReLU alpha, default 0.01)."""
    activation: str = "relu"
    alpha: Optional[float] = None

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        fn = get_activation(self.activation)
        if self.alpha is not None:
            return fn(x, self.alpha), state
        return fn(x), state


@register_layer
@dataclasses.dataclass(frozen=True)
class DropoutLayer(LayerConf):
    """Standalone dropout layer (DL4J DropoutLayer). `dropout` is the drop
    probability; inverted scaling at train time, identity at inference."""
    dropout: float = 0.5

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.maybe_dropout_input(x, train, rng), state


@register_layer
@dataclasses.dataclass(frozen=True)
class OutputLayer(LayerConf):
    """Dense + loss head (DL4J OutputLayer: BaseOutputLayer.computeScore).

    `apply` returns post-activation predictions; `score` computes the loss on
    pre-activation output — autodiff differentiates through both."""
    n_out: int = 0
    n_in: Optional[int] = None
    activation: str = "softmax"
    loss: str = "mcxent"
    weight_init: str = "xavier"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (n_in, self.n_out), n_in, self.n_out, dtype)}
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def preout(self, params, x, train=False, rng=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return get_activation(self.activation)(self.preout(params, x, train, rng)), state

    def score(self, params, x, labels, *, train=False, rng=None, mask=None):
        z = self.preout(params, x, train, rng)
        return get_loss(self.loss)(labels, z, self.activation, mask=mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class CenterLossOutputLayer(LayerConf):
    """Softmax head + center loss (DL4J nn/layers/training/
    CenterLossOutputLayer.java): loss = primary + lambda/2 * ||f - c_y||^2,
    pulling each class's features toward a learned per-class center.

    Design deviation, documented: DL4J updates centers by a non-gradient
    EMA c_y <- (1-alpha) c_y + alpha f. Here centers are ordinary params —
    the gradient of the center term w.r.t. c_y is lambda*(c_y - f), so SGD
    performs the same pull with alpha = lr * lambda (DL4J's own
    gradientCheck mode treats centers exactly this way)."""
    n_out: int = 0
    n_in: Optional[int] = None
    activation: str = "softmax"
    loss: str = "mcxent"
    alpha: float = 0.05             # kept for DL4J config parity
    lambda_: float = 2e-4           # center-loss weight (DL4J lambda)
    weight_init: str = "xavier"
    bias_init: float = 0.0
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (n_in, self.n_out), n_in, self.n_out,
                              dtype),
                  "cL": jnp.zeros((self.n_out, n_in), dtype)}   # class centers
        if self.has_bias:
            params["b"] = jnp.full((self.n_out,), self.bias_init, dtype)
        return params, {}

    def preout(self, params, x, train=False, rng=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return get_activation(self.activation)(
            self.preout(params, x, train, rng)), state

    def score(self, params, x, labels, *, train=False, rng=None, mask=None):
        z = self.preout(params, x, train, rng)
        primary = get_loss(self.loss)(labels, z, self.activation, mask=mask)
        c_y = labels @ params["cL"]                  # (B, n_in) via one-hot
        center = 0.5 * self.lambda_ * jnp.mean(
            jnp.sum((x - c_y) ** 2, axis=-1))
        return primary + center


@register_layer
@dataclasses.dataclass(frozen=True)
class LossLayer(LayerConf):
    """Parameter-free loss head (DL4J LossLayer)."""
    activation: str = "identity"
    loss: str = "mse"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state

    def score(self, params, x, labels, *, train=False, rng=None, mask=None):
        return get_loss(self.loss)(labels, x, self.activation, mask=mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class AutoEncoder(LayerConf):
    """Denoising autoencoder pretrain layer (DL4J nn/conf/layers/AutoEncoder.java,
    impl nn/layers/feedforward/autoencoder/AutoEncoder.java).

    Forward (as a stacked layer) = encoder only. `pretrain_score` corrupts the
    input, encodes, decodes with tied-shape decoder params and scores the
    reconstruction — used by the layerwise-pretraining path
    (MultiLayerNetwork.fit pretrain branch, MultiLayerNetwork.java:1344-1346).
    """
    n_out: int = 0
    n_in: Optional[int] = None
    activation: str = "sigmoid"
    loss: str = "mse"
    corruption_level: float = 0.3
    weight_init: str = "xavier"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        k1, k2 = jax.random.split(key)
        w_init = get_initializer(self.weight_init)
        params = {
            "W": w_init(k1, (n_in, self.n_out), n_in, self.n_out, dtype),
            "b": jnp.zeros((self.n_out,), dtype),
            # decoder bias; decoder weight is tied (W^T), as in DL4J
            "vb": jnp.zeros((n_in,), dtype),
        }
        return params, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        act = get_activation(self.activation)
        return act(x @ params["W"] + params["b"]), state

    def pretrain_score(self, params, x, rng):
        act = get_activation(self.activation)
        if self.corruption_level > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            x_in = jnp.where(keep, x, 0.0)
        else:
            x_in = x
        h = act(x_in @ params["W"] + params["b"])
        recon_pre = h @ params["W"].T + params["vb"]
        return get_loss(self.loss)(x, recon_pre, self.activation)


@register_layer
@dataclasses.dataclass(frozen=True)
class RepeatVector(LayerConf):
    """Repeat a (B, C) vector n times into a (B, n, C) sequence (DL4J
    nn/conf/layers/misc/RepeatVector.java; Keras RepeatVector)."""
    n: int = 1

    def output_type(self, input_type: InputType) -> InputType:
        return InputType(Kind.RNN, (int(self.n), input_type.shape[0]))

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return jnp.repeat(x[:, None, :], int(self.n), axis=1), state


@register_layer
@dataclasses.dataclass(frozen=True)
class PermuteLayer(LayerConf):
    """Permute the non-batch axes (the layer form of DL4J's
    keras/preprocessors/PermutePreprocessor.java; Keras Permute). `dims`
    uses Keras' 1-indexed convention: Permute((2, 1)) swaps the first two
    non-batch axes."""
    dims: Tuple[int, ...] = (1,)

    def output_type(self, input_type: InputType) -> InputType:
        shape = tuple(input_type.shape[d - 1] for d in self.dims)
        if len(shape) == len(input_type.shape):
            return InputType(input_type.kind, shape)
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        perm = (0,) + tuple(int(d) for d in self.dims)
        return jnp.transpose(x, perm), state


@register_layer
@dataclasses.dataclass(frozen=True)
class ReshapeLayer(LayerConf):
    """Reshape the non-batch axes (the layer form of DL4J's
    ReshapePreprocessor, used by modelimport KerasReshape.java; Keras
    Reshape). target: non-batch shape; kind is inferred from its rank
    (1 -> FF, 2 -> (T, C) sequence, 3 -> (H, W, C) image)."""
    target: Tuple[int, ...] = ()    # one dim may be -1 (inferred, as Keras)

    def _resolve(self, in_shape) -> Tuple[int, ...]:
        import numpy as _np
        total = int(_np.prod(in_shape))
        tgt = [int(d) for d in self.target]
        if tgt.count(-1) > 1:
            raise ValueError(f"Reshape: at most one -1 in {self.target}")
        if -1 in tgt:
            rest = int(_np.prod([d for d in tgt if d != -1]))
            if rest <= 0 or total % rest:
                raise ValueError(
                    f"Reshape: cannot infer -1 reshaping {in_shape} "
                    f"into {self.target}")
            tgt[tgt.index(-1)] = total // rest
        if int(_np.prod(tgt)) != total:
            raise ValueError(
                f"Reshape: cannot reshape {tuple(in_shape)} (size {total}) "
                f"into {self.target}")
        return tuple(tgt)

    def output_type(self, input_type: InputType) -> InputType:
        shape = self._resolve(input_type.shape)
        kind = {1: Kind.FF, 2: Kind.RNN, 3: Kind.CNN}.get(len(shape))
        if kind is None:
            raise ValueError(f"Reshape: unsupported rank {len(shape)}")
        return InputType(kind, shape)

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return x.reshape((x.shape[0],) + self._resolve(x.shape[1:])), state
