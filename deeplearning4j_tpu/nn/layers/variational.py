"""Variational autoencoder layer.

Parity target: DL4J nn/conf/layers/variational/VariationalAutoencoder.java and
impl nn/layers/variational/VariationalAutoencoder.java — an unsupervised
pretrain layer with encoder MLP -> (mean, logvar) -> reparameterized sample ->
decoder MLP -> reconstruction distribution. As a stacked (supervised) layer its
forward emits the latent mean, exactly like DL4J's activate() does.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import InputType, LayerConf, register_layer
from deeplearning4j_tpu.nn.initializers import get_initializer


@register_layer
@dataclasses.dataclass(frozen=True)
class VariationalAutoencoder(LayerConf):
    n_out: int = 0                      # latent size
    n_in: Optional[int] = None
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    activation: str = "tanh"            # hidden activation
    pzx_activation: str = "identity"    # activation for q(z|x) mean
    reconstruction_distribution: str = "gaussian"   # gaussian | bernoulli
    num_samples: int = 1
    weight_init: str = "xavier"

    def output_type(self, input_type: InputType) -> InputType:
        return InputType.feed_forward(self.n_out)

    def _mlp_init(self, key, sizes, dtype):
        w_init = get_initializer(self.weight_init)
        layers = []
        for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, sub = jax.random.split(key)
            layers.append({"W": w_init(sub, (a, b), a, b, dtype),
                           "b": jnp.zeros((b,), dtype)})
        return layers

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        k_enc, k_mu, k_dec, k_out = jax.random.split(key, 4)
        enc_sizes = (n_in,) + tuple(self.encoder_layer_sizes)
        dec_sizes = (self.n_out,) + tuple(self.decoder_layer_sizes)
        w_init = get_initializer(self.weight_init)
        h_enc = enc_sizes[-1]
        h_dec = dec_sizes[-1]
        # reconstruction params per input dim: gaussian needs mean+logvar
        recon_mult = 2 if self.reconstruction_distribution == "gaussian" else 1
        params = {
            "enc": self._mlp_init(k_enc, enc_sizes, dtype),
            "muW": w_init(k_mu, (h_enc, 2 * self.n_out), h_enc, 2 * self.n_out, dtype),
            "mub": jnp.zeros((2 * self.n_out,), dtype),
            "dec": self._mlp_init(k_dec, dec_sizes, dtype),
            "outW": w_init(k_out, (h_dec, recon_mult * n_in), h_dec,
                           recon_mult * n_in, dtype),
            "outb": jnp.zeros((recon_mult * n_in,), dtype),
        }
        return params, {}

    def _mlp(self, layers, x):
        act = get_activation(self.activation)
        for l in layers:
            x = act(x @ l["W"] + l["b"])
        return x

    def encode(self, params, x):
        h = self._mlp(params["enc"], x)
        stats = h @ params["muW"] + params["mub"]
        mu, logvar = jnp.split(stats, 2, axis=-1)
        mu = get_activation(self.pzx_activation)(mu)
        return mu, logvar

    def decode(self, params, z):
        h = self._mlp(params["dec"], z)
        return h @ params["outW"] + params["outb"]

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        mu, _ = self.encode(params, x)
        return mu, state

    def pretrain_score(self, params, x, rng):
        """Negative ELBO (reconstruction NLL + KL(q(z|x) || N(0,I)))."""
        mu, logvar = self.encode(params, x)
        kl = -0.5 * jnp.sum(1.0 + logvar - mu**2 - jnp.exp(logvar), axis=-1)
        total_recon = 0.0
        for s in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            out = self.decode(params, z)
            if self.reconstruction_distribution == "gaussian":
                rmu, rlogvar = jnp.split(out, 2, axis=-1)
                nll = 0.5 * jnp.sum(
                    rlogvar + (x - rmu) ** 2 / jnp.exp(rlogvar)
                    + jnp.log(2.0 * jnp.pi), axis=-1)
            elif self.reconstruction_distribution == "bernoulli":
                nll = jnp.sum(jnp.maximum(out, 0) - out * x
                              + jnp.log1p(jnp.exp(-jnp.abs(out))), axis=-1)
            else:
                raise ValueError(self.reconstruction_distribution)
            total_recon = total_recon + nll
        return jnp.mean(total_recon / self.num_samples + kl)

    def reconstruction_probability(self, params, x, rng, num_samples=5):
        """Monte-Carlo estimate of log p(x) (DL4J reconstructionLogProbability)."""
        mu, logvar = self.encode(params, x)
        logps = []
        for s in range(num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mu.shape, mu.dtype)
            z = mu + jnp.exp(0.5 * logvar) * eps
            out = self.decode(params, z)
            if self.reconstruction_distribution == "gaussian":
                rmu, rlogvar = jnp.split(out, 2, axis=-1)
                logp = -0.5 * jnp.sum(
                    rlogvar + (x - rmu) ** 2 / jnp.exp(rlogvar)
                    + jnp.log(2.0 * jnp.pi), axis=-1)
            else:
                logp = -jnp.sum(jnp.maximum(out, 0) - out * x
                                + jnp.log1p(jnp.exp(-jnp.abs(out))), axis=-1)
            logps.append(logp)
        stacked = jnp.stack(logps)
        return jax.scipy.special.logsumexp(stacked, axis=0) - jnp.log(float(num_samples))
