"""Normalization layers.

- BatchNormalization <- DL4J nn/conf/layers/BatchNormalization.java; impl
  nn/layers/normalization/BatchNormalization.java (cuDNN helper
  CudnnBatchNormalizationHelper). XLA fuses the normalize+scale+shift chain;
  running statistics live in the layer *state* pytree (the analog of DL4J's
  global mean/var params updated with `decay`).
- LocalResponseNormalization <- nn/conf/layers/LocalResponseNormalization.java
  (cuDNN helper CudnnLocalResponseNormalizationHelper) — AlexNet-era
  cross-channel LRN.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.conf.base import InputType, LayerConf, register_layer


@register_layer
@dataclasses.dataclass(frozen=True)
class BatchNormalization(LayerConf):
    epsilon: float = 1e-5
    decay: float = 0.9          # running-stat EMA decay (DL4J `decay`)
    gamma_init: float = 1.0
    beta_init: float = 0.0
    lock_gamma_beta: bool = False   # DL4J lockGammaBeta: fixed scale/shift

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        c = input_type.features
        params = {}
        if not self.lock_gamma_beta:
            params = {"gamma": jnp.full((c,), self.gamma_init, dtype),
                      "beta": jnp.full((c,), self.beta_init, dtype)}
        state = {"mean": jnp.zeros((c,), jnp.float32),
                 "var": jnp.ones((c,), jnp.float32)}
        return params, state

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        axes = tuple(range(x.ndim - 1))    # all but channel/feature dim
        stat_t = jnp.promote_types(jnp.float32, x.dtype)
        if train:
            mean = jnp.mean(x.astype(stat_t), axis=axes)
            var = jnp.var(x.astype(stat_t), axis=axes)
            new_state = {
                "mean": self.decay * state["mean"] + (1.0 - self.decay) * mean,
                "var": self.decay * state["var"] + (1.0 - self.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + self.epsilon)
        y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
        if not self.lock_gamma_beta:
            y = y * params["gamma"] + params["beta"]
        else:
            y = y * self.gamma_init + self.beta_init
        return y, new_state


@register_layer
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(LayerConf):
    """Cross-channel LRN: y = x / (k + alpha*sum(x^2 over n channels))^beta."""
    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        sq = x * x
        half = self.n // 2
        # sum over a window of `n` adjacent channels (NHWC last axis)
        summed = lax.reduce_window(
            sq, 0.0, lax.add,
            window_dimensions=(1, 1, 1, self.n),
            window_strides=(1, 1, 1, 1),
            padding=((0, 0), (0, 0), (0, 0), (half, self.n - 1 - half)),
        )
        return x / (self.k + self.alpha * summed) ** self.beta, state
