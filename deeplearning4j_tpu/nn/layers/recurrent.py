"""Recurrent layer family — lax.scan over time, stateful streaming inference.

Parity targets (semantics, not code):
- LSTM / GravesLSTM <- DL4J nn/conf/layers/{LSTM,GravesLSTM}.java; shared math
  nn/layers/recurrent/LSTMHelpers.java (gemm at :206-212,522; cuDNN helper
  CudnnLSTMHelper.java). GravesLSTM adds peephole connections
  (Graves 2013 variant). Here forward is ONE fused gemm per step inside
  lax.scan — the input projection for all timesteps is hoisted out of the
  scan as a single (B*T, in)x(in, 4H) MXU matmul.
- GravesBidirectionalLSTM, Bidirectional wrapper <- nn/conf/layers/...
- SimpleRnn <- nn/conf/layers/SimpleRnn.java
- RnnOutputLayer / RnnLossLayer <- time-distributed loss heads
- LastTimeStep, MaskZeroLayer <- nn/conf/layers/{recurrent,util} wrappers
- rnn_step: single-step stateful inference (MultiLayerNetwork.rnnTimeStep,
  MultiLayerNetwork.java:2806)

Masking follows DL4J semantics (LSTMHelpers.java:355-357): a (B, T) 0/1 mask;
masked steps output zeros and zero the cell/hidden state.

Activations: (batch, time, features) — DL4J is (batch, features, time); the
TPU-native layout keeps features in lanes (last dim = 128-lane axis).

Gate order convention: [i, f, g, o] (input, forget, cell-candidate, output).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.nn.activations import get_activation
from deeplearning4j_tpu.nn.conf.base import InputType, Kind, LayerConf, register_layer
from deeplearning4j_tpu.nn.initializers import get_initializer
from deeplearning4j_tpu.nn.losses import get_loss


def _lstm_scan(xw, h0, c0, R, b, gate_act, cell_act, peep=None, mask=None):
    """Scan an LSTM over time.

    xw: (B, T, 4H) precomputed input projections (input gemm hoisted out of
        the scan — one big MXU matmul instead of T small ones).
    R: (H, 4H) recurrent weights. b: (4H,). peep: optional dict with pi,pf,po
    (H,) peephole weights (GravesLSTM). mask: optional (B, T).
    Returns (hs: (B,T,H), (hT, cT)).
    """
    H = R.shape[0]
    ga = get_activation(gate_act)
    ca = get_activation(cell_act)

    def step(carry, inp):
        h_prev, c_prev = carry
        if mask is not None:
            x_t, m_t = inp
        else:
            x_t = inp
        z = x_t + h_prev @ R + b
        zi, zf, zg, zo = jnp.split(z, 4, axis=-1)
        if peep is not None:
            zi = zi + c_prev * peep["pi"]
            zf = zf + c_prev * peep["pf"]
        i = ga(zi)
        f = ga(zf)
        g = ca(zg)
        c = f * c_prev + i * g
        if peep is not None:
            zo = zo + c * peep["po"]
        o = ga(zo)
        h = o * ca(c)
        if mask is not None:
            m = m_t[:, None]
            h = jnp.where(m > 0, h, 0.0)
            c = jnp.where(m > 0, c, 0.0)
        return (h, c), h

    xs = jnp.swapaxes(xw, 0, 1)                     # (T, B, 4H)
    if mask is not None:
        ms = jnp.swapaxes(mask, 0, 1)               # (T, B)
        (hT, cT), hs = lax.scan(step, (h0, c0), (xs, ms))
    else:
        (hT, cT), hs = lax.scan(step, (h0, c0), xs)
    return jnp.swapaxes(hs, 0, 1), (hT, cT)


@register_layer
@dataclasses.dataclass(frozen=True)
class LSTM(LayerConf):
    """Standard LSTM (no peepholes), DL4J nn/conf/layers/LSTM.java."""
    n_out: int = 0
    n_in: Optional[int] = None
    activation: str = "tanh"            # cell/candidate activation
    gate_activation: str = "sigmoid"    # DL4J gateActivationFunction
    weight_init: str = "xavier"
    forget_gate_bias_init: float = 1.0  # DL4J forgetGateBiasInit

    peephole: bool = False

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        H = self.n_out
        k1, k2, k3 = jax.random.split(key, 3)
        w_init = get_initializer(self.weight_init)
        b = jnp.zeros((4 * H,), dtype)
        # forget-gate bias init (gate order i,f,g,o -> second block)
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        params = {
            "W": w_init(k1, (n_in, 4 * H), n_in, 4 * H, dtype),
            "R": w_init(k2, (H, 4 * H), H, 4 * H, dtype),
            "b": b,
        }
        if self.peephole:
            params["pi"] = jnp.zeros((H,), dtype)
            params["pf"] = jnp.zeros((H,), dtype)
            params["po"] = jnp.zeros((H,), dtype)
        return params, {}

    def _peep(self, params):
        if not self.peephole:
            return None
        return {"pi": params["pi"], "pf": params["pf"], "po": params["po"]}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        hs, _ = self.apply_seq(params, x, None, train=train, rng=rng, mask=mask)
        return hs, state

    def rnn_step(self, params, x_t, carry):
        """Single-step stateful inference (rnnTimeStep). x_t: (B, n_in);
        carry: (h, c) or None."""
        B = x_t.shape[0]
        H = self.n_out
        if carry is None:
            carry = (jnp.zeros((B, H), x_t.dtype), jnp.zeros((B, H), x_t.dtype))
        xw = (x_t @ params["W"])[:, None, :]
        hs, new_carry = _lstm_scan(xw, carry[0], carry[1], params["R"],
                                   params["b"], self.gate_activation,
                                   self.activation, peep=self._peep(params))
        return hs[:, 0, :], new_carry

    def apply_seq(self, params, x, carry, *, train=False, rng=None, mask=None):
        """Sequence forward with explicit initial state — the primitive behind
        truncated BPTT (doTruncatedBPTT, MultiLayerNetwork.java:1315-1317) and
        rnnTimeStep. Returns (y, final_carry)."""
        x = self.maybe_dropout_input(x, train, rng)
        B = x.shape[0]
        H = self.n_out
        if carry is None:
            carry = (jnp.zeros((B, H), x.dtype), jnp.zeros((B, H), x.dtype))
        xw = x @ params["W"]
        hs, new_carry = _lstm_scan(xw, carry[0], carry[1], params["R"],
                                   params["b"], self.gate_activation,
                                   self.activation, peep=self._peep(params),
                                   mask=mask)
        return hs, new_carry


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesLSTM(LSTM):
    """LSTM with peephole connections (Graves 2013), DL4J GravesLSTM.java."""
    peephole: bool = True


@register_layer
@dataclasses.dataclass(frozen=True)
class SimpleRnn(LayerConf):
    """Vanilla RNN: h_t = act(x_t W + h_{t-1} R + b). DL4J SimpleRnn.java."""
    n_out: int = 0
    n_in: Optional[int] = None
    activation: str = "tanh"
    weight_init: str = "xavier"

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        H = self.n_out
        k1, k2 = jax.random.split(key)
        w_init = get_initializer(self.weight_init)
        return {
            "W": w_init(k1, (n_in, H), n_in, H, dtype),
            "R": w_init(k2, (H, H), H, H, dtype),
            "b": jnp.zeros((H,), dtype),
        }, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        hs, _ = self.apply_seq(params, x, None, train=train, rng=rng, mask=mask)
        return hs, state

    def rnn_step(self, params, x_t, carry):
        B = x_t.shape[0]
        H = self.n_out
        act = get_activation(self.activation)
        h_prev = carry if carry is not None else jnp.zeros((B, H), x_t.dtype)
        h = act(x_t @ params["W"] + params["b"] + h_prev @ params["R"])
        return h, h

    def apply_seq(self, params, x, carry, *, train=False, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        B, T, _ = x.shape
        H = self.n_out
        act = get_activation(self.activation)
        xw = x @ params["W"] + params["b"]
        h0 = carry if carry is not None else jnp.zeros((B, H), x.dtype)

        def step(h_prev, inp):
            if mask is not None:
                x_t, m_t = inp
            else:
                x_t = inp
            h = act(x_t + h_prev @ params["R"])
            if mask is not None:
                m = m_t[:, None]
                h = jnp.where(m > 0, h, 0.0)
            return h, h

        xs = jnp.swapaxes(xw, 0, 1)
        if mask is not None:
            ms = jnp.swapaxes(mask, 0, 1)
            hT, hs = lax.scan(step, h0, (xs, ms))
        else:
            hT, hs = lax.scan(step, h0, xs)
        return jnp.swapaxes(hs, 0, 1), hT


@register_layer
@dataclasses.dataclass(frozen=True)
class GRU(LayerConf):
    """Gated recurrent unit. The reference has no GRU (DL4J of this vintage
    ships LSTM/GravesLSTM/SimpleRnn only); this exists for Keras-import
    coverage and stands alone as a layer. Gate order z (update), r (reset),
    candidate h — Keras weight-layout compatible, including the
    `reset_after` variant with its separate recurrent bias."""
    n_out: int = 0
    n_in: Optional[int] = None
    activation: str = "tanh"
    gate_activation: str = "sigmoid"
    weight_init: str = "xavier"
    reset_after: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        H = self.n_out
        k1, k2 = jax.random.split(key)
        w_init = get_initializer(self.weight_init)
        b_shape = (2, 3 * H) if self.reset_after else (3 * H,)
        return {
            "W": w_init(k1, (n_in, 3 * H), n_in, 3 * H, dtype),
            "R": w_init(k2, (H, 3 * H), H, 3 * H, dtype),
            "b": jnp.zeros(b_shape, dtype),
        }, {}

    def _cell(self, params, xw_t, h_prev):
        """One step given precomputed input projections xw_t (B, 3H)."""
        H = self.n_out
        ga = get_activation(self.gate_activation)
        ca = get_activation(self.activation)
        xz, xr, xh = jnp.split(xw_t, 3, axis=-1)
        if self.reset_after:
            hw = h_prev @ params["R"] + params["b"][1]
            hz, hr, hh = jnp.split(hw, 3, axis=-1)
            z = ga(xz + hz)
            r = ga(xr + hr)
            cand = ca(xh + r * hh)
        else:
            # candidate uses (r*h) @ R_h, so only the z|r blocks of R are
            # needed against h_prev — skip the wasted third-gemm columns
            hw = h_prev @ params["R"][:, :2 * H]
            hz, hr = jnp.split(hw, 2, axis=-1)
            z = ga(xz + hz)
            r = ga(xr + hr)
            cand = ca(xh + (r * h_prev) @ params["R"][:, 2 * H:])
        return z * h_prev + (1.0 - z) * cand

    def _input_proj(self, params, x):
        ib = params["b"][0] if self.reset_after else params["b"]
        return x @ params["W"] + ib

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        hs, _ = self.apply_seq(params, x, None, train=train, rng=rng,
                               mask=mask)
        return hs, state

    def rnn_step(self, params, x_t, carry):
        B = x_t.shape[0]
        h_prev = carry if carry is not None \
            else jnp.zeros((B, self.n_out), x_t.dtype)
        h = self._cell(params, self._input_proj(params, x_t[:, None])[:, 0],
                       h_prev)
        return h, h

    def apply_seq(self, params, x, carry, *, train=False, rng=None,
                  mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        B, T, _ = x.shape
        xw = self._input_proj(params, x)      # hoisted input gemm
        h0 = carry if carry is not None \
            else jnp.zeros((B, self.n_out), x.dtype)

        def step(h_prev, inp):
            if mask is not None:
                xw_t, m_t = inp
            else:
                xw_t = inp
            h = self._cell(params, xw_t, h_prev)
            if mask is not None:
                h = jnp.where(m_t[:, None] > 0, h, 0.0)
            return h, h

        xs = jnp.swapaxes(xw, 0, 1)
        if mask is not None:
            ms = jnp.swapaxes(mask, 0, 1)
            hT, hs = lax.scan(step, h0, (xs, ms))
        else:
            hT, hs = lax.scan(step, h0, xs)
        return jnp.swapaxes(hs, 0, 1), hT

@register_layer
@dataclasses.dataclass(frozen=True)
class Bidirectional(LayerConf):
    """Bidirectional wrapper (DL4J nn/conf/layers/recurrent/Bidirectional.java).
    Runs the wrapped RNN forward and on the time-reversed sequence, then
    combines per `mode`: concat | add | mul | ave."""
    layer: Optional[LayerConf] = None
    mode: str = "concat"

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type)
        if self.mode == "concat":
            if inner.kind == Kind.FF:   # e.g. Bidirectional(LastTimeStep(..))
                return InputType.feed_forward(2 * inner.shape[0])
            t, f = inner.shape
            return InputType(Kind.RNN, (t, 2 * f))
        return inner

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        fwd, _ = self.layer.init(k1, input_type, dtype)
        bwd, _ = self.layer.init(k2, input_type, dtype)
        return {"fwd": fwd, "bwd": bwd}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        r1 = r2 = None
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        yf, _ = self.layer.apply(params["fwd"], {}, x, train=train, rng=r1, mask=mask)
        xr = jnp.flip(x, axis=1)
        mr = jnp.flip(mask, axis=1) if mask is not None else None
        yb, _ = self.layer.apply(params["bwd"], {}, xr, train=train, rng=r2, mask=mr)
        if yb.ndim == 3:    # rank-2 when the inner layer is LastTimeStep
            yb = jnp.flip(yb, axis=1)
        if self.mode == "concat":
            y = jnp.concatenate([yf, yb], axis=-1)
        elif self.mode == "add":
            y = yf + yb
        elif self.mode == "mul":
            y = yf * yb
        elif self.mode == "ave":
            y = 0.5 * (yf + yb)
        else:
            raise ValueError(f"Unknown Bidirectional mode {self.mode}")
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesBidirectionalLSTM(Bidirectional):
    """DL4J GravesBidirectionalLSTM = Bidirectional(concat, GravesLSTM)."""
    n_out: int = 0
    n_in: Optional[int] = None

    def __post_init__(self):
        if self.layer is None:
            object.__setattr__(self, "layer",
                               GravesLSTM(n_out=self.n_out, n_in=self.n_in))


@register_layer
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(LayerConf):
    """Time-distributed dense + loss (DL4J RnnOutputLayer): applies the same
    (F_in -> n_out) projection at every step; loss averaged over unmasked steps."""
    n_out: int = 0
    n_in: Optional[int] = None
    activation: str = "softmax"
    loss: str = "mcxent"
    weight_init: str = "xavier"
    has_bias: bool = True

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        n_in = self.n_in or input_type.features
        w_init = get_initializer(self.weight_init)
        params = {"W": w_init(key, (n_in, self.n_out), n_in, self.n_out, dtype)}
        if self.has_bias:
            params["b"] = jnp.zeros((self.n_out,), dtype)
        return params, {}

    def preout(self, params, x, train=False, rng=None):
        x = self.maybe_dropout_input(x, train, rng)
        y = x @ params["W"]
        if self.has_bias:
            y = y + params["b"]
        return y

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return get_activation(self.activation)(self.preout(params, x, train, rng)), state

    def score(self, params, x, labels, *, train=False, rng=None, mask=None):
        z = self.preout(params, x, train, rng)
        return get_loss(self.loss)(labels, z, self.activation, mask=mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class RnnLossLayer(LayerConf):
    """Parameter-free time-distributed loss (DL4J RnnLossLayer)."""
    activation: str = "identity"
    loss: str = "mse"

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def has_params(self):
        return False

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        return get_activation(self.activation)(x), state

    def score(self, params, x, labels, *, train=False, rng=None, mask=None):
        return get_loss(self.loss)(labels, x, self.activation, mask=mask)


@register_layer
@dataclasses.dataclass(frozen=True)
class LastTimeStep(LayerConf):
    """Wraps an RNN layer and emits only the last (unmasked) step's output
    (DL4J nn/conf/layers/recurrent/LastTimeStep.java)."""
    layer: Optional[LayerConf] = None

    def output_type(self, input_type: InputType) -> InputType:
        inner = self.layer.output_type(input_type)
        return InputType.feed_forward(inner.shape[1])

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        return self.layer.init(key, input_type, dtype)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        y, new_state = self.layer.apply(params, state, x, train=train, rng=rng,
                                        mask=mask)
        if mask is None:
            return y[:, -1, :], new_state
        # index of last unmasked step per example; (mask * (t+1)).argmax
        # handles any mask layout (valid-prefix AND the valid-suffix masks
        # produced by Bidirectional's time flip), not just ALIGN_START
        T = y.shape[1]
        pos = jnp.where(mask > 0, jnp.arange(1, T + 1, dtype=jnp.int32), 0)
        idx = jnp.argmax(pos, axis=1).astype(jnp.int32)
        out = jnp.take_along_axis(y, idx[:, None, None], axis=1)[:, 0, :]
        return out, new_state


@register_layer
@dataclasses.dataclass(frozen=True)
class MaskZeroLayer(LayerConf):
    """Zeroes timesteps whose input equals `mask_value`, building a mask for
    the wrapped RNN (DL4J nn/layers/recurrent/MaskZeroLayer.java)."""
    layer: Optional[LayerConf] = None
    mask_value: float = 0.0

    def output_type(self, input_type: InputType) -> InputType:
        return self.layer.output_type(input_type)

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        return self.layer.init(key, input_type, dtype)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        step_is_masked = jnp.all(x == self.mask_value, axis=-1)
        derived = jnp.where(step_is_masked, 0.0, 1.0)
        if mask is not None:
            derived = derived * mask
        return self.layer.apply(params, state, x, train=train, rng=rng,
                                mask=derived)
