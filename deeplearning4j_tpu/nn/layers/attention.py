"""Attention / transformer layers — TPU-native capability.

No DL4J analog (SURVEY.md §5.7: the reference predates attention; its only
long-sequence tools are truncated BPTT + masking). These layers are the
foundation the sequence-parallel / ring-attention machinery
(`parallel/ring.py`) builds on, designed mesh-first:

- activations are (B, T, F) — the framework's RNN kind — so attention
  composes with the existing recurrent/masking infrastructure;
- head and MLP dims are sized for MXU tiles (multiples of 128 recommended);
- `MultiHeadAttention.apply` uses a blockwise-stable softmax and respects
  (B, T) masks with DL4J mask semantics (0 = padded step);
- sharding rules: "model"-axis tensor parallelism shards head projections
  column-wise and output row-wise (Megatron pattern), "seq"-axis sequence
  parallelism is handled by ring attention at the network level.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.base import (
    InputType, Kind, LayerConf, register_layer,
)
from deeplearning4j_tpu.nn.initializers import get_initializer
from deeplearning4j_tpu.util.platform import is_tpu_backend

# --- context-parallel mode -------------------------------------------------
# When the sequence axis is sharded over the mesh (ContextParallelTrainer,
# parallel/context.py), attention must (a) use ring attention instead of
# local dense attention and (b) offset positions by this shard's global
# start. The trainer announces the active mesh axis here; layers read it.
_CONTEXT_PARALLEL_AXIS: Optional[str] = None


class context_parallel:
    """Context manager marking that the T axis is sharded over `axis_name`
    (inside shard_map). Used by ContextParallelTrainer."""

    def __init__(self, axis_name: str):
        self.axis_name = axis_name

    def __enter__(self):
        global _CONTEXT_PARALLEL_AXIS
        self._prev = _CONTEXT_PARALLEL_AXIS
        _CONTEXT_PARALLEL_AXIS = self.axis_name
        return self

    def __exit__(self, *exc):
        global _CONTEXT_PARALLEL_AXIS
        _CONTEXT_PARALLEL_AXIS = self._prev


def _seq_offset(t_local):
    """Global position offset of this shard's sequence slice (0 when the
    sequence axis is not sharded)."""
    if _CONTEXT_PARALLEL_AXIS is None:
        return 0
    return jax.lax.axis_index(_CONTEXT_PARALLEL_AXIS) * t_local


@register_layer
@dataclasses.dataclass(frozen=True)
class LayerNormLayer(LayerConf):
    """Layer normalization over the feature axis."""
    epsilon: float = 1e-5

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        f = input_type.features
        return {"gamma": jnp.ones((f,), dtype),
                "beta": jnp.zeros((f,), dtype)}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        return y * params["gamma"] + params["beta"], state


def _split_heads(x, n_heads):
    b, t, f = x.shape
    return x.reshape(b, t, n_heads, f // n_heads)


def _merge_heads(x):
    b, t, h, d = x.shape
    return x.reshape(b, t, h * d)


def rope(x, positions, base: float = 10000.0):
    """Rotary position embedding on (B, T, H, D)."""
    d = x.shape[-1]
    half = d // 2
    # trig in >= f32 (f64 under float64 gradient checking — a hard f32 cast
    # here corrupts the finite-difference oracle)
    acc_t = jnp.promote_types(jnp.float32, x.dtype)
    freqs = base ** (-jnp.arange(0, half, dtype=acc_t) / half)
    angles = positions[..., None].astype(acc_t) * freqs   # (B?, T, half)
    while angles.ndim < x.ndim:
        angles = angles[..., None, :] if angles.ndim == x.ndim - 1 \
            else angles[None]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
        axis=-1).astype(x.dtype)


def dot_product_attention(q, k, v, *, mask=None, causal=False,
                          q_offset=0, k_offset=0, dropout=0.0, rng=None):
    """Stable softmax attention on (B, T, H, D) tensors.

    mask: (B, Tk) 0/1 key-validity mask (DL4J mask semantics).
    q_offset/k_offset: global position offsets (used by ring attention to
    apply causal masking across sequence shards)."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    # accumulate scores in >= f32 (bf16 inputs -> f32 on the MXU; f64 stays
    # f64 so float64 gradient checks keep a clean numeric oracle)
    acc_t = jnp.promote_types(jnp.float32, q.dtype)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, acc_t))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=acc_t) * scale
    neg = jnp.asarray(-1e30, acc_t)
    if causal:
        qpos = q_offset + jnp.arange(tq)
        kpos = k_offset + jnp.arange(tk)
        causal_mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(causal_mask[None, None], scores, neg)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :].astype(bool), scores, neg)
    # fully-masked query rows (all keys invalid) softmax to uniform garbage;
    # zero them at the end via the weights' max
    m = jnp.max(scores, axis=-1, keepdims=True)
    weights = jnp.exp(scores - jax.lax.stop_gradient(m))
    denom = jnp.sum(weights, axis=-1, keepdims=True)
    weights = weights / jnp.maximum(denom, 1e-30)
    weights = jnp.where(m <= neg / 2, 0.0, weights)
    if dropout > 0.0 and rng is not None:
        keep = 1.0 - dropout
        weights = weights * jax.random.bernoulli(rng, keep, weights.shape) / keep
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)
    return out


@register_layer
@dataclasses.dataclass(frozen=True)
class MultiHeadAttention(LayerConf):
    """Multi-head self-attention over (B, T, F).

    n_out: model width (must divide by n_heads). causal: autoregressive
    masking. use_rope: rotary positions (otherwise positions come from an
    embedding layer upstream). Masks follow DL4J semantics: (B, T) 0/1,
    masked steps neither attend nor get attended to, and their outputs are
    zeroed (MaskZeroLayer behavior)."""
    n_out: int = 0
    n_heads: int = 8
    n_in: Optional[int] = None
    causal: bool = False
    use_rope: bool = True
    attention_dropout: float = 0.0
    weight_init: str = "xavier"
    has_bias: bool = False
    # "dense" | "blockwise" (O(T*block) memory) | "flash" (fused Pallas
    # kernel, ops/flash_attention.py). On TPU, dropout-free blockwise AND
    # flash both run the fused kernel (same algorithm; the kernel is its
    # fastest realization); with attention dropout or off-TPU they use
    # the XLA blockwise lowering. Under a ContextParallelTrainer the
    # layer switches to ring attention (fused per-shard on TPU)
    attention_impl: str = "dense"
    block_size: int = 512

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out {self.n_out} not divisible by "
                             f"n_heads {self.n_heads}")
        if self.use_rope and (self.n_out // self.n_heads) % 2:
            raise ValueError(
                f"rotary embeddings need an even head dim; got "
                f"{self.n_out // self.n_heads} (n_out={self.n_out}, "
                f"n_heads={self.n_heads}) — disable use_rope or resize")
        f_in = self.n_in or input_type.features
        w_init = get_initializer(self.weight_init)
        ks = jax.random.split(key, 4)
        p = {
            "Wq": w_init(ks[0], (f_in, self.n_out), f_in, self.n_out, dtype),
            "Wk": w_init(ks[1], (f_in, self.n_out), f_in, self.n_out, dtype),
            "Wv": w_init(ks[2], (f_in, self.n_out), f_in, self.n_out, dtype),
            "Wo": w_init(ks[3], (self.n_out, self.n_out), self.n_out,
                         self.n_out, dtype),
        }
        if self.has_bias:
            for b in ("bq", "bk", "bv", "bo"):
                p[b] = jnp.zeros((self.n_out,), dtype)
        return p, {}

    def _qkv(self, params, x):
        q = x @ params["Wq"]
        k = x @ params["Wk"]
        v = x @ params["Wv"]
        if self.has_bias:
            q, k, v = q + params["bq"], k + params["bk"], v + params["bv"]
        h = self.n_heads
        return _split_heads(q, h), _split_heads(k, h), _split_heads(v, h)

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        attn_rng = None
        if rng is not None:
            rng, attn_rng = jax.random.split(rng)
        x = self.maybe_dropout_input(x, train, rng)
        q, k, v = self._qkv(params, x)
        t_loc = x.shape[1]
        offset = _seq_offset(t_loc)
        if self.use_rope:
            pos = (offset + jnp.arange(t_loc))[None]
            q = rope(q, pos)
            k = rope(k, pos)
        drop = self.attention_dropout if train else 0.0
        # fused-kernel eligibility, shared by the context-parallel and
        # single-device dispatches (the Pallas interpreter off-TPU would
        # be far slower than XLA; the kernel has no dropout RNG)
        # "blockwise" is the algorithm; on TPU the fused flash kernel IS
        # its fastest realization, so both impls ride it when eligible.
        # DL4J_TPU_FLASH=0 is the first-contact kill switch: if the Pallas
        # kernel miscompiles on real hardware, everything falls back to
        # the lax online-softmax paths without a code edit.
        from deeplearning4j_tpu.util.env import env_flag
        use_flash = (self.attention_impl in ("flash", "blockwise")
                     and drop == 0.0
                     and is_tpu_backend()
                     and env_flag("DL4J_TPU_FLASH"))
        if _CONTEXT_PARALLEL_AXIS is not None:
            if use_flash:
                from deeplearning4j_tpu.parallel.ring import (
                    ring_flash_self_attention,
                )
                out = ring_flash_self_attention(
                    q, k, v, axis_name=_CONTEXT_PARALLEL_AXIS,
                    causal=self.causal, mask=mask,
                    block_q=self.block_size, block_k=self.block_size)
            else:
                from deeplearning4j_tpu.parallel.ring import (
                    ring_self_attention,
                )
                out = ring_self_attention(q, k, v,
                                          axis_name=_CONTEXT_PARALLEL_AXIS,
                                          causal=self.causal, mask=mask,
                                          dropout=drop, rng=attn_rng)
        elif use_flash:
            from deeplearning4j_tpu.ops import flash_attention
            out = flash_attention(q, k, v, mask=mask, causal=self.causal,
                                  block_q=self.block_size,
                                  block_k=self.block_size)
        elif self.attention_impl in ("flash", "blockwise"):
            # off-TPU (the Pallas interpreter would be orders of magnitude
            # slower than XLA), dropout on, or DL4J_TPU_FLASH=0: blockwise
            # recomputation, clamped + padded to the block size like the
            # flash wrapper pads — a sequence shorter than / not divisible
            # by block_size must work, not raise
            from deeplearning4j_tpu.parallel.ring import blockwise_attention
            t = q.shape[1]
            bs = min(self.block_size, t)
            pad = (-t) % bs
            if pad:
                qp, kp, vp = (jnp.pad(a, ((0, 0), (0, pad), (0, 0), (0, 0)))
                              for a in (q, k, v))
                mp = jnp.ones((q.shape[0], t), q.dtype) if mask is None \
                    else mask
                mp = jnp.pad(mp, ((0, 0), (0, pad)))
                out = blockwise_attention(qp, kp, vp, block_size=bs,
                                          causal=self.causal, mask=mp,
                                          dropout=drop,
                                          rng=attn_rng)[:, :t]
            else:
                out = blockwise_attention(q, k, v, block_size=bs,
                                          causal=self.causal, mask=mask,
                                          dropout=drop, rng=attn_rng)
        else:
            out = dot_product_attention(
                q, k, v, mask=mask, causal=self.causal,
                dropout=drop, rng=attn_rng)
        y = _merge_heads(out) @ params["Wo"]
        if self.has_bias:
            y = y + params["bo"]
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class TransformerBlock(LayerConf):
    """Pre-norm transformer block: LN -> MHA -> +res -> LN -> MLP -> +res.

    One declarative unit so deep stacks stay compact in configs (the zoo's
    TransformerLM stacks these). mlp_ratio*n_out is the hidden width."""
    n_out: int = 0
    n_heads: int = 8
    mlp_ratio: int = 4
    causal: bool = True
    use_rope: bool = True
    activation: str = "gelu"
    attention_dropout: float = 0.0
    residual_dropout: float = 0.0
    weight_init: str = "xavier"
    attention_impl: str = "dense"       # forwarded to MultiHeadAttention
    block_size: int = 512

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def _sub(self):
        attn = MultiHeadAttention(
            n_out=self.n_out, n_heads=self.n_heads, causal=self.causal,
            use_rope=self.use_rope, attention_dropout=self.attention_dropout,
            weight_init=self.weight_init, attention_impl=self.attention_impl,
            block_size=self.block_size)
        ln = LayerNormLayer()
        return ln, attn

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        f_in = input_type.features
        if f_in != self.n_out:
            raise ValueError(
                f"TransformerBlock requires input width == n_out "
                f"({f_in} != {self.n_out}); project with a DenseLayer first")
        ln, attn = self._sub()
        ks = jax.random.split(key, 4)
        ln_p, _ = ln.init(ks[0], input_type, dtype)
        attn_p, _ = attn.init(ks[1], input_type, dtype)
        hidden = self.mlp_ratio * self.n_out
        w_init = get_initializer(self.weight_init)
        return {
            "ln1": ln_p,
            "attn": attn_p,
            "ln2": {"gamma": jnp.ones((self.n_out,), dtype),
                    "beta": jnp.zeros((self.n_out,), dtype)},
            "W1": w_init(ks[2], (self.n_out, hidden), self.n_out, hidden,
                         dtype),
            "b1": jnp.zeros((hidden,), dtype),
            "W2": w_init(ks[3], (hidden, self.n_out), hidden, self.n_out,
                         dtype),
            "b2": jnp.zeros((self.n_out,), dtype),
        }, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.nn.activations import get_activation
        ln, attn = self._sub()
        r1 = r2 = None
        if rng is not None:
            rng, r1, r2 = jax.random.split(rng, 3)
        h, _ = ln.apply(params["ln1"], {}, x)
        a, _ = attn.apply(params["attn"], {}, h, train=train, rng=r1,
                          mask=mask)
        if train and self.residual_dropout > 0 and r2 is not None:
            keep = 1.0 - self.residual_dropout
            a = a * jax.random.bernoulli(r2, keep, a.shape) / keep
        x = x + a
        h, _ = ln.apply(params["ln2"], {}, x)
        h = get_activation(self.activation)(h @ params["W1"] + params["b1"])
        h = h @ params["W2"] + params["b2"]
        y = x + h
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, state


@register_layer
@dataclasses.dataclass(frozen=True)
class MoEFeedForward(LayerConf):
    """Mixture-of-experts FFN with top-2 soft routing — the expert-parallel
    (EP) building block. Experts stack on a leading axis sized n_experts;
    sharding rule P("model") on that axis = expert parallelism (each model-
    axis group holds a subset of experts; the einsum dispatch becomes an
    all-to-all under the partitioner).

    Capacity-less dense routing (every token scores every expert, weighted
    by the top-2 normalized gates): simpler than Switch-style dispatch and
    XLA-friendly (no dynamic shapes); fine up to ~16 experts."""
    n_out: int = 0
    n_experts: int = 8
    top_k: int = 2
    mlp_ratio: int = 4
    activation: str = "gelu"
    weight_init: str = "xavier"

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        f_in = input_type.features
        if f_in != self.n_out:
            raise ValueError("MoEFeedForward requires input width == n_out")
        hidden = self.mlp_ratio * self.n_out
        w_init = get_initializer(self.weight_init)
        ks = jax.random.split(key, 3)
        e = self.n_experts

        def ew(k, shape, fi, fo):
            keys = jax.random.split(k, e)
            return jnp.stack([w_init(keys[i], shape, fi, fo, dtype)
                              for i in range(e)])

        return {
            "Wg": w_init(ks[0], (f_in, e), f_in, e, dtype),
            "W1": ew(ks[1], (f_in, hidden), f_in, hidden),
            "b1": jnp.zeros((e, hidden), dtype),
            "W2": ew(ks[2], (hidden, self.n_out), hidden, self.n_out),
            "b2": jnp.zeros((e, self.n_out), dtype),
        }, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        from deeplearning4j_tpu.nn.activations import get_activation
        gates = jax.nn.softmax(x @ params["Wg"], axis=-1)   # (B, T, E)
        if self.top_k < self.n_experts:
            top_vals, _ = jax.lax.top_k(gates, self.top_k)
            thresh = top_vals[..., -1:]
            gates = jnp.where(gates >= thresh, gates, 0.0)
            gates = gates / jnp.maximum(
                jnp.sum(gates, -1, keepdims=True), 1e-9)
        act = get_activation(self.activation)
        h = jnp.einsum("btf,efh->bteh", x, params["W1"]) + params["b1"]
        h = act(h)
        y = jnp.einsum("bteh,eho->bteo", h, params["W2"]) + params["b2"]
        out = jnp.einsum("bteo,bte->bto", y, gates)
        if mask is not None:
            out = out * mask[..., None].astype(out.dtype)
        return out, state


@register_layer
@dataclasses.dataclass(frozen=True)
class PositionalEmbeddingLayer(LayerConf):
    """Learned absolute position embeddings added to (B, T, F)."""
    max_length: int = 2048

    def output_type(self, input_type: InputType) -> InputType:
        return input_type

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        f = input_type.features
        return {"P": jax.random.normal(key, (self.max_length, f), dtype)
                * 0.02}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        t = x.shape[1]
        start = _seq_offset(t)
        if isinstance(start, int) and start == 0:
            if t > self.max_length:
                raise ValueError(
                    f"sequence length {t} exceeds max_length "
                    f"{self.max_length}")
            pos = params["P"][:t]
        else:    # context-parallel shard: take this shard's slice
            # the global length is static (shard count x local length);
            # reject overflow at trace time — dynamic_slice would silently
            # clamp late shards onto the tail rows
            global_t = t * jax.lax.psum(1, _CONTEXT_PARALLEL_AXIS)
            if int(global_t) > self.max_length:
                raise ValueError(
                    f"global sequence length {int(global_t)} exceeds "
                    f"max_length {self.max_length}")
            pos = jax.lax.dynamic_slice_in_dim(params["P"], start, t)
        return x + pos[None], state


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingSequenceLayer(LayerConf):
    """Token-id sequence -> embedding sequence: (B, T) or (B, T, 1) int ids
    to (B, T, n_out). The sequence analog of EmbeddingLayer (DL4J gained
    EmbeddingSequenceLayer later than the reference vintage; needed here as
    the transformer LM front end)."""
    n_out: int = 0
    n_in: Optional[int] = None      # vocabulary size (required)
    weight_init: str = "normal"

    def output_type(self, input_type: InputType) -> InputType:
        t = input_type.shape[0]
        return InputType(Kind.RNN, (t, self.n_out))

    def init(self, key, input_type: InputType, dtype=jnp.float32):
        if not self.n_in:
            raise ValueError("EmbeddingSequenceLayer requires n_in "
                             "(vocabulary size)")
        table = jax.random.normal(key, (self.n_in, self.n_out),
                                  dtype) * 0.02
        return {"W": table}, {}

    def apply(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim == 3:
            x = x[..., 0]
        idx = x.astype(jnp.int32)
        y = jnp.take(params["W"], idx, axis=0)
        if mask is not None:
            y = y * mask[..., None].astype(y.dtype)
        return y, state
