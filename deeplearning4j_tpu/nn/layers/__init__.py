from deeplearning4j_tpu.nn.layers.feedforward import (
    DenseLayer, EmbeddingLayer, ActivationLayer, DropoutLayer,
    OutputLayer, CenterLossOutputLayer, LossLayer, AutoEncoder,
    ElementWiseMultiplicationLayer,
    RepeatVector, PermuteLayer, ReshapeLayer,
)
from deeplearning4j_tpu.nn.layers.convolution import (
    ConvolutionLayer, Convolution1DLayer, SubsamplingLayer,
    Subsampling1DLayer, Upsampling2D, ZeroPaddingLayer, GlobalPoolingLayer,
    Deconvolution2D, SeparableConvolution2D, DepthwiseConvolution2D,
    SpaceToDepthLayer, SpaceToBatchLayer, Cropping2D, CnnLossLayer,
    Cropping1D, Upsampling1D, ZeroPadding1DLayer,
    LocallyConnected1D, LocallyConnected2D,
)
from deeplearning4j_tpu.nn.layers.normalization import (
    BatchNormalization, LocalResponseNormalization,
)
from deeplearning4j_tpu.nn.layers.recurrent import (
    GRU, LSTM, GravesLSTM, GravesBidirectionalLSTM, SimpleRnn, Bidirectional,
    RnnOutputLayer, RnnLossLayer, LastTimeStep, MaskZeroLayer,
)
from deeplearning4j_tpu.nn.layers.variational import VariationalAutoencoder
from deeplearning4j_tpu.nn.layers.samediff import SameDiffLayer, FrozenLayerWrapper
from deeplearning4j_tpu.nn.layers.objdetect import Yolo2OutputLayer
from deeplearning4j_tpu.nn.layers.attention import (
    EmbeddingSequenceLayer, LayerNormLayer, MoEFeedForward,
    MultiHeadAttention, PositionalEmbeddingLayer, TransformerBlock,
)

__all__ = [
    "DenseLayer", "EmbeddingLayer", "ActivationLayer", "DropoutLayer",
    "OutputLayer", "CenterLossOutputLayer", "LossLayer", "AutoEncoder",
    "ElementWiseMultiplicationLayer",
    "RepeatVector", "PermuteLayer", "ReshapeLayer",
    "ConvolutionLayer", "Convolution1DLayer", "SubsamplingLayer",
    "Subsampling1DLayer", "Upsampling2D", "ZeroPaddingLayer",
    "GlobalPoolingLayer", "Deconvolution2D", "SeparableConvolution2D",
    "DepthwiseConvolution2D", "SpaceToDepthLayer", "SpaceToBatchLayer",
    "Cropping2D", "CnnLossLayer",
    "Cropping1D", "Upsampling1D", "ZeroPadding1DLayer",
    "LocallyConnected1D", "LocallyConnected2D",
    "BatchNormalization", "LocalResponseNormalization",
    "GRU", "LSTM", "GravesLSTM", "GravesBidirectionalLSTM", "SimpleRnn",
    "Bidirectional", "RnnOutputLayer", "RnnLossLayer", "LastTimeStep",
    "MaskZeroLayer", "VariationalAutoencoder", "SameDiffLayer",
    "FrozenLayerWrapper", "Yolo2OutputLayer",
    "MultiHeadAttention", "TransformerBlock", "MoEFeedForward",
    "LayerNormLayer", "PositionalEmbeddingLayer", "EmbeddingSequenceLayer",
]
